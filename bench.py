"""Headline benchmark: puzzles/sec/chip on a hard unique-solution 9×9 corpus.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Modes (BENCH_MODE env var):
  throughput (default) — solve the cached hard corpus, report puzzles/s/chip
    vs the ≥100k/chip north-star target (BASELINE.md).
  latency — start a warmed single node (the real CLI + HTTP stack), fire the
    README 8-clue puzzle at POST /solve repeatedly, report p50 in ms vs the
    <5 ms north-star target (vs_baseline = 5/p50, so ≥1.0 meets it). The
    reference's only latency artifact is its execution_time log line
    (reference node.py:681-683; 168.4 s on this same puzzle, BASELINE.md).
    Note: through a tunneled TPU each blocking request pays the tunnel RTT
    (~70 ms here); p95/min and the request breakdown go to stderr so the
    artifact records both the serving-stack cost and the link cost.
  farm — the reference's flagship multi-node scenario on its own terms:
    4 CLI node processes, anchor join, a 5-hole 9×9 posted to a non-anchor
    master; warm p50 in ms vs the reference's measured 180 ms (which
    returned an incomplete board — completeness is asserted here;
    SURVEY.md §3.2). vs_baseline = 180/p50.
  concurrent — multi-tenant serving: BENCH_CONCURRENT_CLIENTS (default 64)
    closed-loop HTTP clients against ONE node; aggregate puzzles/s with
    the request coalescer on vs the seed's serialized per-request path,
    plus client p50/p99 and the realized batch-fill from /stats
    (parallel/coalescer.py). vs_baseline = coalesced/serialized speedup.
  overload — open-loop Poisson arrivals at BENCH_OVERLOAD_X (default 2×)
    the measured closed-loop capacity, against a no-admission baseline
    node and an admission+deadline+adaptive node under the IDENTICAL
    schedule (serving/admission.py): goodput, shed rate, p50/p99 of
    admitted requests. vs_baseline = admission/no-admission goodput.
  coldstart — the compiler plane A/B (ISSUE 4): fresh child processes
    measure time-to-first-solve / tier-0-warm / fully-warm under {cold,
    persistent-XLA-cache, AOT-artifact} on CPU (engine tiered warmup +
    compilecache/). Artifact benchmarks/coldstart_pr4.json; vs_baseline
    = warm-vs-cold first-solve speedup over the ≥3× acceptance bar.
  obs-overhead — the observability planes' cost proof (ISSUE 6 + the
    ISSUE 10 per-bucket cost accounting, which records per BATCH on the
    serving path): tracing-on vs --no-obs aggregate puzzles/s under
    BENCH_OBS_CLIENTS (default 64) closed-loop clients (acceptance: on ≥
    0.97× off), plus an injected breaker-trip incident whose
    flight-recorder dump must carry the poisoned request's span with
    per-stage timings, and the traced node's live engine.cost block.
    Artifact benchmarks/obs_overhead_pr10.json (PR 6's bound held with
    cost accounting on; obs_overhead_pr6.json is the pre-cost baseline).
  hotloop — the solver hot-loop A/B (ISSUE 7): the PR 7 loop (dense
    prefix-gather compaction, one-hot merges, packed bitplane analysis)
    vs ``legacy_loop=True`` on the hard corpus, pinned core, paired
    alternating windows, plus lane-step/idle-lane counter proofs and a
    one-straggler phase showing finished boards stop iterating.
    Artifact benchmarks/hotloop_pr7.json; ``--smoke`` for CI plumbing.
  continuous — the pipelined segment-boundary A/B (ISSUE 15): the PR 15
    boundary (buffer donation, digest-only two-phase fetch, overlapped
    host refill — the continuous default) vs the PR 12 boundary
    (--no-segment-pipeline: full-row fetch, serial boundaries),
    replaying one Poisson schedule at 2x measured capacity on a mixed
    easy/deep pool in order-flipped paired windows; sustained pps from
    the engine.cost continuous deltas (headline, acceptance >= 1.10),
    boundary_host_ms + fetch-bytes evidence, deadline-conditioned p99,
    bit-parity hashes vs the closed-loop batch reference, and a 25x25
    digest-vs-full-row byte probe. Artifact
    benchmarks/pipeline_pr15.json (the PR 12 continuous-vs-closed A/B
    is benchmarks/continuous_pr12.json history); ``--smoke`` for CI.
  cache — the canonical-form answer cache A/B (ISSUE 13): a
    Zipf-distributed overload mix — viral puzzles arriving as random
    SYMMETRIES of themselves (cache/canonical.py random_symmetry), the
    exact shape exact-match caching cannot serve — replayed identically
    by a cache-on and a cache-off node in order-flipped paired windows
    (run_paired_windows). Headline: deadline-conditioned goodput paired
    ratio; plus hit rate, hit-path p50 vs the cache-off dispatch p50
    (acceptance: ≥100× below), and sha256 parity of answers across arms
    for commonly-answered requests (a cached answer must be
    bit-identical to a computed one). Artifact
    benchmarks/cache_pr13.json; ``--smoke`` for CI.
  chaos — the fleet autopilot's proof (ISSUE 14): an M-node fleet under
    open-loop task-farm overload with a worker SIGKILL'd, a worker
    SIGSTOP/SIGCONT-cycled (the live straggler), and a worker's engine
    poisoned over POST /debug/faults mid-run; autopilot ON vs
    --no-autopilot under the identical schedule + fault timeline.
    Headline: fault-window deadline-conditioned goodput ratio (≥1.2
    acceptance), plus the SLO fast-burn recover-with-no-operator-action
    timeline, hedge fired/won/budget counters, and 100% host-side rule
    verification of every answer in both arms. Artifact
    benchmarks/chaos_pr14.json; ``--smoke`` for CI.
  tpu-window — first-class claim-window harness (the fold of the
    tpu_session_retry*.sh scanners): scan the relay ports, bake the
    compile plane within a budget, run the headline ladder, and emit a
    machine-readable window report on EVERY exit path (claimed-and-ran /
    claim-failed / compile-budget-exceeded). Artifact
    benchmarks/window_report_pr7.json; runs on CPU as the CI-verified
    fallback.
  mesh-scaling — the mesh-parallel serving plane's proof (ISSUE 8), on
    fake devices (--xla_force_host_platform_device_count children via
    parallel/sim.py): per device count {1, 4, ...} a fresh child builds a
    mesh engine, serves coalesced traffic + batch solves, and reports the
    batch-split counter evidence (output sharding: N devices × rows each),
    solution hashes (byte-identical across topologies), idle-lane loop
    counters, and — in a SECOND fresh child per count — the sharded AOT
    cold start (warm sources aot:*, no recompile). Artifact
    benchmarks/mesh_pr8.json. Counter evidence only: fake devices share
    the host's cores, so the wall-clock multi-chip headline stays
    reserved for --mode tpu-window on real hardware. ``--smoke`` for CI.

Modes are also selectable as ``python bench.py --mode <name>``.

The reference publishes no benchmark numbers (BASELINE.md); its measured
equivalent is ~0.006 puzzles/s on the README 8-clue board (168.4 s, single
node). The north-star target from BASELINE.json is ≥100k 17-clue-class
puzzles/sec on a v4-8, i.e. ~25k/chip naively — we report per-chip throughput
and normalize vs_baseline against the 100k/chip stretch goal so a value of
1.0 means the stretch target is met on one chip.

Corpus: seeded, generated once and cached — minimal-ish unique-solution
puzzles (blanking down while uniqueness holds, ~22-28 clues), the same
difficulty class as the Gordon Royle 17-clue set the north star names
(that corpus isn't redistributable here; zero-egress environment).
"""

import json
import os
import sys
import time

# BENCH_SIZE selects the board config: 9 (headline, the north-star corpus),
# 16 hexadoku, or 25. Per-size stretch targets normalize vs_baseline (9×9 is
# BASELINE.json's ≥100k/chip; larger boards scaled as rough cell-count-cubed
# stretch goals — no reference numbers exist at any size, BASELINE.md).
BENCH_SIZE = int(os.environ.get("BENCH_SIZE", "9"))
_DEFAULT_BATCH = {9: 16384, 16: 2048, 25: 512}
if BENCH_SIZE not in _DEFAULT_BATCH:
    sys.exit(f"BENCH_SIZE must be one of {sorted(_DEFAULT_BATCH)}, got {BENCH_SIZE}")
BENCH_BATCH = int(
    os.environ.get("BENCH_BATCH", str(_DEFAULT_BATCH[BENCH_SIZE]))
)
BENCH_REPEATS = int(os.environ.get("BENCH_REPEATS", "5"))
_HOLES = {9: 64, 16: 140, 25: 320}
CORPUS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "benchmarks",
    f"corpus_{BENCH_SIZE}x{BENCH_SIZE}_hard_{BENCH_BATCH}.npz",
)
TARGET_PER_CHIP = {9: 100_000.0, 16: 10_000.0, 25: 1_000.0}[BENCH_SIZE]
# ONE definition of the shared persistent compile cache: the TPU session
# (benchmarks/tpu_session_r5.py) imports this, so a compile paid in any
# claim window is reused by every later bench/session run.
COMPILE_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "benchmarks",
    ".jax_cache_tpu",
)


def _load_corpus():
    import numpy as np

    if os.path.exists(CORPUS_PATH):
        return np.load(CORPUS_PATH)["boards"]
    from sudoku_solver_distributed_tpu.models import generate_batch

    boards = generate_batch(
        BENCH_BATCH,
        _HOLES[BENCH_SIZE],
        size=BENCH_SIZE,
        seed=20260729,
        unique=True,
    )
    os.makedirs(os.path.dirname(CORPUS_PATH), exist_ok=True)
    np.savez_compressed(CORPUS_PATH, boards=boards)
    return boards


def run_paired_windows(arms, pairs, ratio_of):
    """THE shared paired-window measurement discipline — one definition
    for ``--mode hotloop``, ``--mode obs-overhead``, and ``--mode
    continuous`` (it used to be re-copied per mode).

    ``arms`` is an ordered list of ``(name, fn)`` where each ``fn()``
    runs ONE measurement window for that arm and returns its scalar
    measure (seconds, pps — the caller's choice; side bookkeeping lives
    in the closure). Every pair runs each arm once, with the execution
    order FLIPPED on odd pairs: consecutive windows are not exchangeable
    on a small shared host (burst credits / throttle decay inside a
    pair), and a fixed order turns that decay into fake arm overhead.

    ``ratio_of`` is ``(numerator_name, denominator_name)``; the headline
    is the MEDIAN of per-pair ratios (``statistics.median`` — the even-
    count case averages the middle pair rather than picking the luckier
    window) — robust to episodic single-window scheduler stalls, unlike
    the aggregate ratio.

    Returns ``(rows, ratios_sorted, median_ratio)``; each row carries
    ``{"order": [...], <name>: measure..., "ratio": r}``.
    """
    import statistics

    names = [n for n, _ in arms]
    fns = dict(arms)
    num, den = ratio_of
    rows = []
    for p in range(pairs):
        order = list(names) if p % 2 == 0 else list(reversed(names))
        vals = {}
        for name in order:
            vals[name] = fns[name]()
        rows.append(
            {
                "order": order,
                **{n: round(vals[n], 4) for n in names},
                "ratio": round(vals[num] / vals[den], 4) if vals[den] else 0.0,
            }
        )
    ratios = sorted(r["ratio"] for r in rows)
    median = round(statistics.median(ratios), 4) if ratios else 0.0
    return rows, ratios, median


def main():
    import threading

    import jax

    # BENCH_PLATFORM reroutes throughput runs (e.g. =cpu for smoke tests);
    # the config route is the only one that works pre-init here — this
    # environment's sitecustomize re-exports JAX_PLATFORMS over caller env
    # vars (see __graft_entry__._ensure_devices).
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    # Share the measurement session's persistent compile cache: a serving-
    # config compile that succeeded in ANY earlier claim window (or CPU
    # run) is reused instead of re-paid — on the flaky tunnel, compiles
    # are the scarce resource (benchmarks/tpu_session_r5.py).
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("JAX_COMPILATION_CACHE_DIR", COMPILE_CACHE_DIR),
    )
    # env overrides respected for all three knobs (same convention as
    # tests/conftest.py and the session script — code-review r5)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        int(os.environ.get("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", 0)),
    )
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes",
        int(os.environ.get("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", 0)),
    )

    # Watchdog: on a pooled/tunneled accelerator a stale pool-side claim
    # makes backend init hang indefinitely, and (round-5 discovery) the
    # FIRST COMPILE can also block unboundedly when the relay's
    # remote-compile port closes mid-window (docs/OPERATIONS.md). Both
    # phases fail fast with a diagnosable message instead of wedging the
    # caller's pipeline into a SIGKILL/parsed:null (the round-3 shape).
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "900"))
    compile_timeout = float(os.environ.get("BENCH_COMPILE_TIMEOUT_S", "600"))
    init_done = threading.Event()
    compile_done = threading.Event()
    compile_armed = threading.Event()

    def _watchdog():
        if not init_done.wait(init_timeout):
            print(
                f"# FATAL: accelerator backend init exceeded "
                f"{init_timeout:.0f}s — pooled-chip claim unavailable "
                f"(stale claim? see docs/OPERATIONS.md); rerun when the "
                f"claim frees or set BENCH_PLATFORM=cpu",
                file=sys.stderr,
                flush=True,
            )
            os._exit(3)
        compile_armed.wait()
        if not compile_done.wait(compile_timeout):
            print(
                f"# FATAL: first transfer/compile blocked past "
                f"{compile_timeout:.0f}s — wedged relay (window closed "
                f"mid-session) or a pathologically slow compile; raise "
                f"BENCH_COMPILE_TIMEOUT_S if the latter "
                f"(benchmarks/tpu_session_r5.log)",
                file=sys.stderr,
                flush=True,
            )
            os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()

    # test hooks: simulate a stale-claim init hang — on the first attempt
    # only (…_ONCE, a flag file marks attempts) or on every attempt
    # (…_ALWAYS). tests/test_bench_modes.py exercises the retry loop with
    # these; a real hang can't be staged without wedging the actual claim.
    # Neither fires in the CPU-fallback child: the hang being simulated IS
    # accelerator claim acquisition, which the CPU backend never does.
    fallback_reason = os.environ.get("BENCH_FALLBACK_REASON")
    in_fallback = bool(fallback_reason)
    if not in_fallback:
        hang_flag = os.environ.get("BENCH_FAKE_INIT_HANG_ONCE")
        if hang_flag and not os.path.exists(hang_flag):
            open(hang_flag, "w").close()
            time.sleep(init_timeout * 100)  # parked until the watchdog fires
        if os.environ.get("BENCH_FAKE_INIT_HANG_ALWAYS") == "1":
            time.sleep(init_timeout * 100)
    elif os.environ.get("BENCH_FAKE_FALLBACK_FAIL") == "1":
        sys.exit(9)  # test hook: drive the parent's last-resort JSON line

    # touch the backend FIRST so the watchdog window covers exactly the
    # claim acquisition — corpus generation below is host-side work that
    # can legitimately take long on a first uncached run. A backend that
    # RAISES (e.g. "UNAVAILABLE: TPU backend setup/compile error" from a
    # sick pooled terminal — the round-3 failure mode) is the same claim
    # failure as a hang: exit rc=3 so the parent retries / falls back
    # instead of dying with no JSON on stdout.
    try:
        n_chips = max(1, len(jax.devices()))
    except RuntimeError as e:
        print(
            f"# FATAL: accelerator backend init raised: {e!r:.500} — "
            f"pooled-chip claim unavailable (docs/OPERATIONS.md)",
            file=sys.stderr,
            flush=True,
        )
        sys.exit(3)
    init_done.set()  # backend is up; disarm the claim watchdog

    if in_fallback and os.environ.get("BENCH_FAKE_FALLBACK_HANG") == "1":
        # test hook: a post-init stall (the real slow-fallback shape, e.g.
        # uncached corpus regeneration) — drives the parent's reserve timeout
        time.sleep(3600)

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.ops import (
        cpu_serving_config,
        serving_config,
        solve_batch,
        spec_for_size,
    )

    spec = spec_for_size(BENCH_SIZE)
    boards = _load_corpus()
    clues = int((boards[0] > 0).sum())
    # THE serving configuration — ops.SERVING_CONFIG is the single definition
    # site shared with SolverEngine and __graft_entry__ (per-size staged
    # depth, fused waves, locked sets; measured rationale in ops/config.py),
    # so this number measures exactly what the serving engine runs. The
    # labeled CPU-fallback record instead reports the CPU backend at its
    # measured best (ops/config.CPU_SERVING_OVERRIDES — the TPU-tuned waves
    # lose on CPU), with the config named in the record.
    cfg = (
        cpu_serving_config(BENCH_SIZE)
        if in_fallback
        else serving_config(BENCH_SIZE)
    )
    solve = jax.jit(lambda g: solve_batch(g, spec, **cfg))

    # Transfer + first compile under the compile watchdog: a blocked
    # device transfer or remote-compile RPC must exit 3 (parent retries /
    # falls back), not hang into the driver's outer SIGKILL. NOT armed in
    # the CPU-fallback child (same rule as the init hooks above): the
    # hazard being guarded is the accelerator relay, and killing a slow
    # legitimate CPU compile would destroy the guaranteed *_cpu_fallback
    # record (code-review r5). Exiting mid-compile CAN wedge the pooled
    # claim (docs/OPERATIONS.md) — but the alternative is the driver's
    # outer SIGKILL minutes later, which wedges it just the same AND
    # leaves no parseable artifact; exiting on our own terms records the
    # diagnostic and lets the parent's next attempt probe the window.
    if not in_fallback:
        compile_armed.set()
        if os.environ.get("BENCH_FAKE_COMPILE_HANG") == "1":
            time.sleep(compile_timeout * 100)  # test hook: wedged relay
    dev_boards = jnp.asarray(boards)
    res = jax.block_until_ready(solve(dev_boards))
    compile_done.set()
    assert bool(np.asarray(res.solved).all()), "bench: unsolved boards!"

    # Throughput measurement: repeats are dispatched back-to-back (JAX async
    # dispatch) and synchronized once at the end, the way a saturated serving
    # engine runs — per-call host/tunnel round-trip latency is amortized, so
    # the number reflects sustained device throughput, not link RTT. A
    # blocking per-call latency run is reported on stderr for reference.
    t0 = time.perf_counter()
    outs = [solve(dev_boards) for _ in range(BENCH_REPEATS)]
    jax.block_until_ready(outs[-1])
    sustained = (time.perf_counter() - t0) / BENCH_REPEATS

    times = []
    for _ in range(BENCH_REPEATS):
        t0 = time.perf_counter()
        res = jax.block_until_ready(solve(dev_boards))
        times.append(time.perf_counter() - t0)
    best = min(times)
    pps_per_chip = BENCH_BATCH / min(best, sustained) / n_chips

    metric = f"puzzles_per_sec_per_chip_hard{BENCH_SIZE}x{BENCH_SIZE}"
    record = {
        "metric": metric,
        "value": round(pps_per_chip, 1),
        "unit": "puzzles/s/chip",
        "vs_baseline": round(pps_per_chip / TARGET_PER_CHIP, 4),
    }
    # Labeled CPU fallback (VERDICT r3 task 1b): when the pooled-chip claim
    # never frees, the parent re-runs this child on the CPU backend with the
    # reason in the environment — the artifact then records an honest,
    # clearly-tagged number instead of parsed:null.
    if fallback_reason:
        record["metric"] = metric + "_cpu_fallback"
        record["fallback_reason"] = fallback_reason
        record["platform"] = jax.devices()[0].platform
        record["config"] = cfg  # json serializes the depth tuple as a list
    print(json.dumps(record))
    print(
        f"# batch={BENCH_BATCH} repeats={BENCH_REPEATS} "
        f"sustained={sustained*1000:.1f}ms blocking_best={best*1000:.1f}ms "
        f"chips={n_chips} clues≈{clues} iters={int(res.iters)}",
        file=sys.stderr,
    )


README_PUZZLE = [
    [0, 0, 0, 1, 0, 0, 0, 0, 0],
    [0, 0, 0, 3, 2, 0, 0, 0, 0],
    [0, 0, 0, 0, 0, 9, 0, 0, 0],
    [0, 0, 0, 0, 0, 0, 0, 7, 0],
    [0, 0, 0, 0, 0, 0, 0, 0, 0],
    [0, 0, 0, 9, 0, 0, 0, 0, 0],
    [0, 0, 0, 0, 0, 0, 9, 0, 0],
    [0, 0, 0, 0, 0, 0, 0, 0, 3],
    [0, 0, 0, 0, 0, 0, 0, 0, 0],
]  # reference README.md:20 — its 168.4 s single-node board (BASELINE.md)


def main_latency():
    import subprocess
    import urllib.request

    import numpy as np

    # pid-derived ports so a stale node from a crashed earlier run can't
    # answer this run's probes and get benchmarked in place of our child
    http_port = 18000 + os.getpid() % 700
    udp_port = http_port - 1000
    reps = int(os.environ.get("BENCH_LATENCY_REPS", "40"))
    repo = os.path.dirname(os.path.abspath(__file__))
    body = json.dumps({"sudoku": README_PUZZLE}).encode()

    def post_solve(timeout=300.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{http_port}/solve",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=timeout) as r:
            payload = json.loads(r.read())
        return (time.perf_counter() - t0) * 1e3, payload

    # handicap 0: the artifact measures the serving stack (warm compiled
    # engine + HTTP + P2P bookkeeping), not the reference's simulated-work
    # sleeps, which -h scales (reference node.py:89-95)
    # BENCH_PLATFORM=cpu serves from the local CPU backend — the co-located-
    # device proxy when the only TPU is behind a high-RTT tunnel.
    # BENCH_FRONTIER=N routes the /solve through the mesh-sharded frontier
    # race (N speculative states per chip) instead of the bucket path.
    platform = os.environ.get("BENCH_PLATFORM")
    extra = ["--platform", platform] if platform else []
    # "0" must mean off (the CLI's own convention) or the metric would be
    # labeled frontier while the node serves the bucket path
    frontier = os.environ.get("BENCH_FRONTIER")
    frontier = frontier if frontier and int(frontier) > 0 else None
    if frontier:
        extra += ["--frontier", frontier]
    proc = subprocess.Popen(
        [
            sys.executable, os.path.join(repo, "node.py"),
            "-p", str(http_port), "-s", str(udp_port), "-h", "0",
            # server-side timing (utils/profiling.RequestMetrics): the
            # artifact separates serving-stack cost from link RTT — through
            # a tunneled TPU the e2e number is dominated by the tunnel,
            # which says nothing about the stack (VERDICT r2 missing #4)
            "--metrics",
            # the metric is the ENGINE serving path: the answer cache
            # would serve rep 2..N of the identical puzzle from its LRU
            # (bench.py --mode cache measures that plane on its own)
            "--no-answer-cache",
        ]
        + extra,
        cwd=repo,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        # wait for HTTP up, then for warm buckets: solve until fast twice
        deadline = time.time() + 180
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node exited rc={proc.returncode} before serving "
                    f"(ports {http_port}/{udp_port} busy?)"
                )
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/stats", timeout=2
                )
                break
            except Exception:
                if time.time() > deadline:
                    raise RuntimeError("node did not come up") from None
                time.sleep(0.5)
        fast = 0
        while fast < 2 and time.time() < deadline:
            ms, _ = post_solve()
            fast = fast + 1 if ms < 500 else 0
        if fast < 2:
            print(
                "# WARNING: warm criterion (2 consecutive <500ms solves) not "
                "met before deadline — measured p50 may include compile time",
                file=sys.stderr,
            )

        times = []
        for _ in range(reps):
            ms, payload = post_solve()
            assert payload[0][3] == 1 and all(
                all(v != 0 for v in row) for row in payload
            ), "bad README solve"
            times.append(ms)
        times = np.asarray(times)
        p50 = float(np.percentile(times, 50))
        p95 = float(np.percentile(times, 95))
        # server-side view of the same requests (RTT excluded): the node's
        # own /solve timing from RequestMetrics
        server = {}
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/metrics", timeout=5
            ) as r:
                server = json.loads(r.read()).get("/solve", {})
        except Exception as e:  # noqa: BLE001 — metrics are best-effort
            print(f"# /metrics scrape failed: {e!r}", file=sys.stderr)
        metric = "p50_solve_http_latency_readme9x9"
        if frontier:
            metric += "_frontier"
        record = {
            "metric": metric,
            "value": round(p50, 2),
            "unit": "ms",
            "vs_baseline": round(5.0 / p50, 4),
        }
        if server:
            record["server_p50_ms"] = server.get("p50_ms")
            record["server_p95_ms"] = server.get("p95_ms")
        print(json.dumps(record))
        print(
            f"# reps={reps} platform={platform or 'default'} "
            f"frontier={frontier or 'off'} "
            f"p50={p50:.2f}ms p95={p95:.2f}ms "
            f"min={times.min():.2f}ms max={times.max():.2f}ms "
            f"server-side /solve: {server or 'n/a'} "
            f"(e2e is blocking HTTP; on a tunneled chip each request also "
            f"pays the host<->TPU link RTT, which the server-side numbers "
            f"exclude)",
            file=sys.stderr,
        )
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


def main_farm():
    """4-node task-farm benchmark: the reference's flagship path, its rules.

    The reference's only multi-node measurement is a 4-process localhost
    farm solving a 5-hole 9×9 through `/solve` — 0.18 s, and the returned
    board had an unsolved cell (SURVEY.md §3.2 [verified live]). This mode
    reproduces that exact scenario on this stack — 4 CLI node processes,
    anchor join, the request posted to a NON-anchor node (every node can be
    master, SURVEY.md) — and reports warm p50 with completeness asserted on
    every reply. vs_baseline = 180 ms / p50: ≥1.0 beats the reference's
    incomplete-board time with complete boards.
    """
    import subprocess
    import urllib.request

    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch

    n_nodes = int(os.environ.get("BENCH_FARM_NODES", "4"))
    reps = int(os.environ.get("BENCH_FARM_REPS", "20"))
    # BENCH_FARM_HOLES: the farmed-cell count. 5 reproduces the reference's
    # flagship measurement; 40-60 is the realistic-load profile VERDICT r4
    # task 6 asks for (the farm answers each hole with a full-board worker
    # solve, so cost scales ~linearly in holes — see OPERATIONS.md).
    holes = int(os.environ.get("BENCH_FARM_HOLES", "5"))
    repo = os.path.dirname(os.path.abspath(__file__))
    base = 19000 + os.getpid() % 600
    http_ports = [base + i for i in range(n_nodes)]
    udp_ports = [p - 1000 for p in http_ports]
    # default cpu: n node processes must not each claim the (single,
    # pooled) accelerator — on a one-claim tunnel they would serialize or
    # wedge (docs/OPERATIONS.md). Export BENCH_PLATFORM= to override.
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    extra = ["--platform", platform] if platform else []

    board = generate_batch(1, holes, seed=180, unique=True)[0].tolist()
    body = json.dumps({"sudoku": board}).encode()
    target = http_ports[1]  # non-anchor master, the SURVEY-verified flow

    def scrape_stats():
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{target}/stats", timeout=5
            ) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def post_solve(timeout=300.0):
        req = urllib.request.Request(
            f"http://127.0.0.1:{target}/solve",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=timeout) as r:
            payload = json.loads(r.read())
        return (time.perf_counter() - t0) * 1e3, payload

    procs = []
    try:
        for i in range(n_nodes):
            cmd = [
                sys.executable, os.path.join(repo, "node.py"),
                "-p", str(http_ports[i]), "-s", str(udp_ports[i]), "-h", "0",
                # the metric is the task FARM path; a cached repeat
                # would bypass it (--mode cache owns that plane)
                "--no-answer-cache",
            ] + extra
            if i > 0:
                cmd += ["-a", f"localhost:{udp_ports[0]}"]
            procs.append(
                subprocess.Popen(
                    cmd, cwd=repo,
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                )
            )
            time.sleep(0.3)  # anchor first; joiners flood in join order

        # convergence: the master-to-be sees all n-1 peers at /network
        deadline = time.time() + 240
        while True:
            if any(p.poll() is not None for p in procs):
                raise RuntimeError("a node exited before serving")
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{target}/network", timeout=2
                ) as r:
                    view = json.loads(r.read())
                ids = set(view)
                for vs in view.values():
                    ids.update(vs)
                if len(ids) >= n_nodes:
                    break
            except Exception:
                pass
            if time.time() > deadline:
                raise RuntimeError("farm did not converge")
            time.sleep(0.5)

        # warm: every worker compiles its engine on first dispatch
        fast = 0
        while fast < 2 and time.time() < deadline:
            ms, _ = post_solve()
            fast = fast + 1 if ms < 500 else 0

        stats_before = scrape_stats()
        times = []
        for _ in range(reps):
            ms, payload = post_solve()
            assert all(
                all(v != 0 for v in row) for row in payload
            ), "farm returned an incomplete board"
            times.append(ms)
        times = np.asarray(times)
        stats_after = scrape_stats()
        p50 = float(np.percentile(times, 50))
        # baselines: the reference has exactly two multi-node datapoints —
        # 180 ms at 5 holes (incomplete board, SURVEY.md §3.2) and 25 s at
        # 30 holes (5 cells unsolved, §6). vs_baseline is only emitted at a
        # comparable hole count; other workloads have no reference number
        # and a ratio would be apples-to-oranges (code-review r5).
        if holes <= 5:
            baseline_ms = 180.0
        elif 25 <= holes <= 35:
            baseline_ms = 25000.0
        else:
            baseline_ms = None
        record = {
            "metric": f"p50_solve_http_{n_nodes}node_farm_{holes}hole9x9",
            "value": round(p50, 2),
            "unit": "ms",
            "vs_baseline": (
                round(baseline_ms / p50, 4) if baseline_ms else None
            ),
        }
        # cost-model evidence (VERDICT r4 task 6): each farmed request
        # costs ~holes worker full-board solves + 1 authoritative master
        # solve; the gossiped validation counters carry the network-wide
        # engine effort (per-sweep accounting, SURVEY.md §2)
        if stats_before and stats_after:
            record["validations_delta_total"] = (
                stats_after["all"]["validations"]
                - stats_before["all"]["validations"]
            )
            record["expected_engine_solves"] = reps * (holes + 1)
        print(json.dumps(record))
        print(
            f"# nodes={n_nodes} reps={reps} holes={holes} "
            f"platform={platform or 'default'} "
            f"p50={p50:.2f}ms p95={float(np.percentile(times, 95)):.2f}ms "
            f"min={times.min():.2f}ms baseline="
            f"{f'{baseline_ms:.0f}ms' if baseline_ms else 'none (no comparable reference datapoint)'} "
            f"(reference returned INCOMPLETE boards at both its farm "
            f"datapoints; completeness asserted here on every reply)",
            file=sys.stderr,
        )

        if os.environ.get("BENCH_FARM_KILL") == "1":
            # The reference's third measured scenario (SURVEY.md §6): a
            # 30-hole board with one worker SIGKILL'd mid-solve — 25 s and
            # 5 cells left unsolved there. Here the heartbeat detector
            # prunes the dead worker, its in-flight cell requeues, and the
            # board must come back complete.
            import threading

            kill_board = generate_batch(1, 30, seed=181, unique=True)[
                0
            ].tolist()
            kbody = json.dumps({"sudoku": kill_board}).encode()
            victim = procs[-1]

            def post_kill():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{target}/solve",
                    data=kbody,
                    headers={"Content-Type": "application/json"},
                )
                t0 = time.perf_counter()
                killer = threading.Timer(0.01, victim.kill)
                killer.start()
                with urllib.request.urlopen(req, timeout=300) as r:
                    payload = json.loads(r.read())
                killer.cancel()
                return (time.perf_counter() - t0) * 1e3, payload

            ms, payload = post_kill()
            victim.wait()
            assert all(
                all(v != 0 for v in row) for row in payload
            ), "crash-recovery solve returned an incomplete board"
            print(
                f"# kill-scenario: 30-hole board, worker SIGKILL'd "
                f"mid-solve -> complete in {ms:.0f}ms (reference: 25 s with "
                f"5 cells unsolved, SURVEY.md §6)",
                file=sys.stderr,
            )

    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def main_concurrent():
    """Multi-tenant serving benchmark: K client threads against ONE node.

    The coalescer story end-to-end (ISSUE 1 tentpole): concurrent /solve
    requests are micro-batched into the engine's warm buckets
    (parallel/coalescer.py), so aggregate puzzles/s should scale well past
    the single-stream rate instead of collapsing to serialized per-request
    latency × N (the seed's behavior: every request behind one lock).

    Two phases under IDENTICAL load (BENCH_CONCURRENT_CLIENTS closed-loop
    clients, default 64, for BENCH_CONCURRENT_SECS, default 8 s), one JSON
    line:
      1. seed baseline — a ``--seed-serving`` node: every request
         serialized behind one lock, batch-1 device calls, HTTP/1.0 on the
         stock 5-deep accept queue — the seed's serving stack, bit for bit;
      2. coalesced — a default node: requests micro-batched into warm
         buckets, keep-alive transport, deep accept queue; aggregate
         puzzles/s, client-side p50/p99, and the realized batch-fill
         scraped from the node's /stats serving block (--serving-stats).

    vs_baseline = coalesced aggregate / seed aggregate (the ≥3× acceptance
    ratio). Default platform cpu: one node process must not claim the
    pooled tunneled chip by accident (same rule as farm mode); export
    BENCH_PLATFORM=tpu for the real thing.
    """
    import subprocess
    import threading
    import urllib.request

    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch

    clients = int(os.environ.get("BENCH_CONCURRENT_CLIENTS", "64"))
    secs = float(os.environ.get("BENCH_CONCURRENT_SECS", "8"))
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    repo = os.path.dirname(os.path.abspath(__file__))
    http_port = 17000 + os.getpid() % 700
    udp_port = http_port - 1000

    # Request mix: the committed HARD unique-solution corpus (the headline
    # throughput class), so per-request device time dominates localhost
    # HTTP overhead and the measurement compares serving paths, not socket
    # plumbing. BENCH_CONCURRENT_HOLES overrides with generated boards of
    # that hole count (easier ≈ shorter device calls).
    holes = os.environ.get("BENCH_CONCURRENT_HOLES")
    if holes:
        boards = generate_batch(
            32, int(holes), seed=20260802, unique=False
        )
    else:
        hard = os.path.join(repo, "benchmarks", "corpus_9x9_hard_64.npz")
        if os.path.exists(hard):
            boards = np.load(hard)["boards"][:32]
        else:
            boards = generate_batch(32, 64, seed=20260802, unique=True)
    bodies = [
        json.dumps({"sudoku": b.tolist()}).encode() for b in boards
    ]

    import socket

    requests_bytes = [
        b"POST /solve HTTP/1.1\r\nHost: bench\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: %d\r\n\r\n%s" % (len(b), b)
        for b in bodies
    ]

    class RawConn:
        """Minimal raw-socket HTTP client. http.client's response
        machinery (email-parsed headers) costs ~1 ms of GIL-held time per
        request — at 64 client threads that makes the LOAD GENERATOR the
        measurement's bottleneck. Both phases use this same client, so
        the A/B stays fair. Keep-alive when the server speaks HTTP/1.1;
        against the seed-serving node (HTTP/1.0) every response closes
        the connection and the next request pays a fresh TCP handshake —
        exactly the seed's per-request transport cost."""

        def __init__(self, timeout=300.0):
            self.timeout = timeout
            self.sock = None
            self.rf = None

        def _connect(self):
            self.sock = socket.create_connection(
                ("127.0.0.1", http_port), timeout=self.timeout
            )
            self.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            self.rf = self.sock.makefile("rb", -1)

        def close(self):
            if self.sock is not None:
                try:
                    self.rf.close()
                    self.sock.close()
                except OSError:
                    pass
            self.sock = self.rf = None

        def post(self, k):
            """One /solve; returns latency ms. Raises AssertionError on a
            non-200 or incomplete solution (never transient), OSError on
            transport trouble."""
            if self.sock is None:
                self._connect()
            t0 = time.perf_counter()
            self.sock.sendall(requests_bytes[k % len(requests_bytes)])
            status_line = self.rf.readline(65537)
            if not status_line:
                raise OSError("server closed connection")
            parts = status_line.split(None, 2)
            clen = 0
            close = parts[0] == b"HTTP/1.0"
            while True:
                h = self.rf.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                key, _, value = h.partition(b":")
                key = key.strip().lower()
                if key == b"content-length":
                    clen = int(value)
                elif key == b"connection":
                    close = value.strip().lower() == b"close"
            raw = self.rf.read(clen)
            dt = (time.perf_counter() - t0) * 1e3
            if close:
                self.close()  # next post() reconnects
            # a 400 ("No solution found" / "Invalid request") must never
            # count as a solved puzzle — iterating its JSON error OBJECT
            # yields key strings, which the cell check below would
            # happily accept
            assert parts[1] == b"200", (
                f"/solve answered {parts[1]!r}: {raw[:120]!r}"
            )
            payload = json.loads(raw)
            assert isinstance(payload, list) and all(
                all(v != 0 for v in row) for row in payload
            ), "incomplete board from /solve"
            return dt

    def post_solve(k, timeout=300.0):
        conn = RawConn(timeout)
        try:
            return conn.post(k)
        finally:
            conn.close()

    def scrape(path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}{path}", timeout=5
        ) as r:
            return json.loads(r.read())

    # bucket ladder sized to the client count: 64 closed-loop clients can
    # never queue more than 64 boards, and background-compiling the default
    # 512/4096 buckets would contend with the measurement window for cores
    # (on CPU the 4096 compile alone is ~a minute)
    top = 1
    while top < clients:
        top *= 8
    buckets = ",".join(str(b) for b in (1, 8, 64, 512, 4096) if b <= top)

    def with_node(extra_flags, fn):
        proc = subprocess.Popen(
            [
                sys.executable, os.path.join(repo, "node.py"),
                "-p", str(http_port), "-s", str(udp_port), "-h", "0",
                "--serving-stats", "--metrics", "--buckets", buckets,
                # the A/B isolates the coalescer/transport planes: the
                # answer cache would serve the cycling client pool from
                # its LRU on both arms (--mode cache owns that plane)
                "--no-answer-cache",
            ]
            + (["--platform", platform] if platform else [])
            + extra_flags,
            cwd=repo,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            deadline = time.time() + 180
            while True:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"node exited rc={proc.returncode} before serving"
                    )
                try:
                    scrape("/stats")
                    break
                except Exception:
                    if time.time() > deadline:
                        raise RuntimeError("node did not come up") from None
                    time.sleep(0.5)
            # full-ladder warm gate: every bucket pre-compiled (engine.warmed
            # at /metrics), so neither phase races the background warmup
            while time.time() < deadline:
                eng_m = scrape("/metrics").get("engine", {})
                # "warmed" now flips at tier-0 (ISSUE 4); the A/B
                # gates on the FULL ladder so neither phase races
                # the background widening
                if eng_m.get("fully_warmed", eng_m.get("warmed")):
                    break
                time.sleep(0.5)
            else:
                raise RuntimeError("engine warmup did not finish")
            fast = 0  # warm criterion, as in latency mode
            while fast < 2 and time.time() < deadline:
                fast = fast + 1 if post_solve(0) < 500 else 0
            return fn()
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()

    def drive(n_threads):
        """Closed-loop clients for ``secs``; returns (pps, lat_ms_list,
        error_count). A client that hits a transient transport error
        (the seed phase's HTTP/1.0 + 5-deep accept queue drops/RSTs
        connections under this very load — that collapse is part of what
        is being measured) reconnects and keeps offering load, so both
        phases sustain identical demand end to end."""
        stop = time.perf_counter() + secs
        lats, errs, failures = [], [], []
        lock = threading.Lock()

        def client(i):
            k = i
            my, my_errs = [], 0
            conn = RawConn()
            try:
                while time.perf_counter() < stop:
                    try:
                        my.append(conn.post(k))
                    except AssertionError as e:
                        # an incomplete board / non-200 is never transient:
                        # record it for the post-join assert (raising here
                        # would only kill THIS thread, and the bench would
                        # exit 0 with silently reduced load)
                        failures.append(f"client {i}: {e}")
                        return
                    except Exception:  # noqa: BLE001 — transport-level
                        my_errs += 1
                        conn.close()
                    k += n_threads
            finally:
                conn.close()
                with lock:
                    lats.extend(my)
                    errs.append(my_errs)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_threads)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert not failures, failures[:3]
        assert lats, "no request completed inside the measurement window"
        return len(lats) / wall, lats, sum(errs)

    # Phase 1 — the seed's serving stack under the FULL client load (the
    # honest denominator: this is what the seed delivers to these exact
    # clients), plus a 1-client pass for the single-stream engine rate
    # (reported for context; saturation vs single-stream are different
    # collapses and the record carries both).
    def seed_phase():
        single_pps, _, _ = drive(1)
        pps, _, errors = drive(clients)
        return single_pps, pps, errors

    single_pps, serial_pps, serial_errs = with_node(
        ["--seed-serving"], seed_phase
    )

    def coalesced():
        pps, lats, errors = drive(clients)
        serving = scrape("/stats").get("serving", {})
        return pps, lats, errors, serving

    # On the CPU fallback, cap coalesced device calls at the SIMD sweet
    # spot: the lockstep batch runs every board for the worst board's
    # iteration count, so a wide batch of mixed hard boards costs more
    # per board than slices of 8 (measured: batch-8 2758 boards/s vs
    # batch-64 854 on 2 cores — engine.coalesce_max_batch rationale).
    # On a real chip the widest bucket is the whole point; no cap there.
    coal_flags = ["--coalesce-max-batch", "8"] if platform == "cpu" else []
    pps, lats, coal_errs, serving = with_node(coal_flags, coalesced)
    lats = np.asarray(lats)
    record = {
        "metric": f"concurrent_solve_puzzles_per_sec_{clients}c_9x9",
        "value": round(pps, 1),
        "unit": "puzzles/s",
        # the acceptance ratio: coalesced aggregate over the seed stack's
        # aggregate under identical load (>=3 required)
        "vs_baseline": round(pps / serial_pps, 3) if serial_pps else None,
        "serialized_pps": round(serial_pps, 1),
        "single_stream_pps": round(single_pps, 1),
        "p50_ms": round(float(np.percentile(lats, 50)), 2),
        "p99_ms": round(float(np.percentile(lats, 99)), 2),
        "batch_fill_avg": serving.get("batch_fill_avg"),
        "batch_fill_max": serving.get("batch_fill_max"),
        "transport_errors": {"seed": serial_errs, "coalesced": coal_errs},
    }
    print(json.dumps(record))
    print(
        f"# clients={clients} secs={secs} boards={holes or 'hard-corpus'} "
        f"platform={platform or 'default'} requests={len(lats)} "
        f"seed={serial_pps:.1f}pps (single-stream {single_pps:.1f}, "
        f"{serial_errs} transport errors) coalesced={pps:.1f}pps "
        f"({coal_errs} errors) speedup={pps / serial_pps:.2f}x "
        f"serving={serving}",
        file=sys.stderr,
    )


def main_overload():
    """Open-loop overload A/B: the admission control plane's proof.

    Closed-loop benchmarks (``--mode concurrent``) can never overload the
    server — each client waits for its answer before offering the next
    request, so demand self-throttles to capacity. Real fleets don't:
    arrivals are open-loop, and when they exceed capacity the only choices
    are unbounded queueing (every answer arbitrarily late) or admission
    control (serving/admission.py). This mode measures both under the
    SAME Poisson arrival schedule at ``BENCH_OVERLOAD_X`` (default 2×) the
    measured closed-loop saturation rate:

      1. calibrate — closed-loop clients against a default node: the
         sustainable capacity (also the warm-up);
      2. baseline — the same no-admission node under open-loop overload:
         every request is accepted, the queue grows for the whole run,
         and answers come back arbitrarily late (the collapse being
         demonstrated);
      3. admission — a node with ``--admission-capacity`` +
         ``--default-deadline-ms`` + ``--adaptive-coalesce`` under the
         identical schedule: excess arrivals answer 429 in microseconds,
         admitted requests complete inside their budget.

    GOODPUT is deadline-conditioned in both phases: a 200 that arrives
    after ``BENCH_OVERLOAD_DEADLINE_MS`` is a wasted device call, not a
    served user — under overload that is the only honest definition
    (raw completed pps is reported alongside). One JSON line (the
    BENCH_* artifact): vs_baseline = admission goodput over baseline
    goodput; ``goodput_vs_closed_loop`` carries the ISSUE 2 acceptance
    ratio (≥ 0.9 wanted). Clients send ``Connection: close`` so the
    server's worker pool cycles per request instead of pinning workers
    to idle keep-alive sockets; both nodes run with a WIDE worker pool
    (``--http-workers 512``) so the pending backlog lives where the
    admission layer can see it — the A/B isolates the admission plane,
    not the transport cap. Default platform cpu (same pooled-chip rule
    as farm/concurrent).
    """
    import subprocess
    import threading
    import urllib.request

    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch

    secs = float(os.environ.get("BENCH_OVERLOAD_SECS", "6"))
    cal_secs = float(os.environ.get("BENCH_OVERLOAD_CAL_SECS", "3"))
    cal_clients = int(os.environ.get("BENCH_OVERLOAD_CLIENTS", "32"))
    xmult = float(os.environ.get("BENCH_OVERLOAD_X", "2"))
    deadline_ms = float(os.environ.get("BENCH_OVERLOAD_DEADLINE_MS", "500"))
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    size = int(os.environ.get("BENCH_OVERLOAD_SIZE", "9"))
    repo = os.path.dirname(os.path.abspath(__file__))
    http_port = 16000 + os.getpid() % 700
    udp_port = http_port - 1000

    # Request mix: the committed adversarial corpus (worst-case-mined
    # boards of the ordinary class — a few ms each; NOT the deep-tail
    # corpus, whose ~1.6 s service time can never meet a 500 ms deadline
    # and would turn goodput into a measure of the mix, not the control
    # plane). BENCH_OVERLOAD_HOLES overrides with generated boards.
    holes = os.environ.get("BENCH_OVERLOAD_HOLES")
    if holes or size != 9:
        boards = generate_batch(
            16,
            int(holes) if holes else _HOLES.get(size, 64),
            size=size,
            seed=20260802,
            unique=False,
        )
    else:
        adv_path = os.path.join(
            repo, "benchmarks", "corpus_9x9_adversarial_128.npz"
        )
        if os.path.exists(adv_path):
            boards = np.load(adv_path)["boards"][:16]
        else:
            boards = generate_batch(16, 64, seed=20260802, unique=True)
    bodies = [json.dumps({"sudoku": b.tolist()}).encode() for b in boards]

    # Resource isolation: pin the node to ONE core and the generator to
    # the rest. Colocated on a shared 2-core host, an unpinned A/B is
    # unmeasurable — the server's ~600 pps two-core capacity exceeds
    # what the generator can offer at 2× while competing for the same
    # cores, so "overload" degenerates into GIL thrash on both sides.
    # One dedicated core per role gives a stable ~300 pps server and a
    # generator with honest 2× headroom.
    cores = (
        sorted(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else []
    )
    pin = (
        len(cores) >= 2
        and platform == "cpu"
        and os.environ.get("BENCH_OVERLOAD_NO_PIN") != "1"
        and __import__("shutil").which("taskset") is not None
    )
    node_prefix = []
    if pin:
        node_prefix = ["taskset", "-c", str(cores[0])]
        os.sched_setaffinity(0, set(cores[1:]))

    import socket

    def _has_zero_cell(raw):
        # a zero CELL renders as "0" bounded by row/list punctuation;
        # multi-digit values like 10/20 never match (16x16/25x25 safe)
        return b"[0," in raw or b" 0," in raw or b" 0]" in raw

    def request_bytes(k, keepalive):
        b = bodies[k % len(bodies)]
        conn_hdr = b"" if keepalive else b"Connection: close\r\n"
        return (
            b"POST /solve HTTP/1.1\r\nHost: bench\r\n"
            b"Content-Type: application/json\r\n"
            b"%sContent-Length: %d\r\n\r\n%s" % (conn_hdr, len(b), b)
        )

    class Client:
        """Raw-socket /solve client; returns (status:int, latency_ms).
        Raises OSError on transport trouble. With keepalive=False every
        request rides a fresh connection (the open-loop phases); the
        calibration phase reuses one (closed-loop, like --mode
        concurrent)."""

        def __init__(self, keepalive, timeout=30.0):
            self.keepalive = keepalive
            self.timeout = timeout
            self.sock = None
            self.rf = None

        def close(self):
            if self.sock is not None:
                try:
                    self.rf.close()
                    self.sock.close()
                except OSError:
                    pass
            self.sock = self.rf = None

        def post(self, k):
            if self.sock is None:
                self.sock = socket.create_connection(
                    ("127.0.0.1", http_port), timeout=self.timeout
                )
                self.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                self.rf = self.sock.makefile("rb", -1)
            t0 = time.perf_counter()
            self.sock.sendall(request_bytes(k, self.keepalive))
            status_line = self.rf.readline(65537)
            if not status_line:
                raise OSError("server closed connection")
            parts = status_line.split(None, 2)
            status = int(parts[1])
            clen, close = 0, not self.keepalive
            while True:
                h = self.rf.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                key, _, value = h.partition(b":")
                key = key.strip().lower()
                if key == b"content-length":
                    clen = int(value)
                elif key == b"connection":
                    close = value.strip().lower() == b"close"
            raw = self.rf.read(clen)
            dt = (time.perf_counter() - t0) * 1e3
            if close:
                self.close()
            if status == 200:
                # cheap completeness screen on every reply, full JSON parse
                # on a sample: the load GENERATOR shares the box with the
                # server, and json-decoding every board at 2x overload is
                # measurable GIL time stolen from the thing being measured
                assert raw.startswith(b"[[") and not _has_zero_cell(raw), (
                    "incomplete board from /solve"
                )
                if k % 32 == 0:
                    payload = json.loads(raw)
                    assert isinstance(payload, list) and all(
                        all(v != 0 for v in row) for row in payload
                    ), "incomplete board from /solve"
            return status, dt

    def scrape(path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}{path}", timeout=5
        ) as r:
            return json.loads(r.read())

    # bucket ladder bounded like --mode concurrent: compiling 512/4096
    # buckets would contend with the measurement for cores on CPU
    buckets = "1,8,64"
    coal_flags = ["--coalesce-max-batch", "8"] if platform == "cpu" else []

    def with_node(extra_flags, fn):
        proc = subprocess.Popen(
            node_prefix
            + [
                sys.executable, os.path.join(repo, "node.py"),
                "-p", str(http_port), "-s", str(udp_port), "-h", "0",
                "--board-size", str(size),
                "--serving-stats", "--metrics", "--buckets", buckets,
                # the A/B isolates the ADMISSION plane: the answer cache
                # would absorb the Poisson repeat mass before admission
                # on both arms (--mode cache owns that plane)
                "--no-answer-cache",
                # worker pool sized past the client's connection count:
                # the overload backlog must reach the admission layer
                # (and, on the baseline node, the coalescer queue)
                # instead of piling up as unobservable unaccepted
                # connections — the A/B isolates the admission plane,
                # not the transport cap
                "--http-workers", "256",
            ]
            + (["--platform", platform] if platform else [])
            + coal_flags
            + extra_flags,
            cwd=repo,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        try:
            deadline = time.time() + 180
            while True:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"node exited rc={proc.returncode} before serving"
                    )
                try:
                    scrape("/stats")
                    break
                except Exception:
                    if time.time() > deadline:
                        raise RuntimeError("node did not come up") from None
                    time.sleep(0.5)
            while time.time() < deadline:
                eng_m = scrape("/metrics").get("engine", {})
                # "warmed" now flips at tier-0 (ISSUE 4); the A/B
                # gates on the FULL ladder so neither phase races
                # the background widening
                if eng_m.get("fully_warmed", eng_m.get("warmed")):
                    break
                time.sleep(0.5)
            else:
                raise RuntimeError("engine warmup did not finish")
            c = Client(keepalive=True)
            fast = 0
            while fast < 2 and time.time() < deadline:
                status, ms = c.post(0)
                fast = fast + 1 if status == 200 and ms < 500 else 0
            c.close()
            return fn()
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()

    def closed_loop(n_threads, run_secs):
        """Keep-alive closed-loop drive; returns completed pps."""
        stop = time.perf_counter() + run_secs
        counts = []
        lock = threading.Lock()

        def client(i):
            c, n, k = Client(keepalive=True), 0, i
            try:
                while time.perf_counter() < stop:
                    try:
                        status, _ = c.post(k)
                        if status == 200:
                            n += 1
                    except OSError:
                        c.close()
                    k += n_threads
            finally:
                c.close()
                with lock:
                    counts.append(n)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_threads)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return sum(counts) / wall

    def open_loop(schedule):
        """Offer the Poisson schedule over K persistent keep-alive
        connections with PIPELINED sends: each connection has a writer
        thread firing its round-robin slice of arrivals at their
        scheduled absolute times (one pre-built sendall — microseconds,
        so a 2-core box can offer multiples of its own serving capacity)
        and a reader thread draining in-order responses. Returns
        (ok_lats_ms, shed, errors, late_sends, wall_s); wall runs to the
        LAST completion, so late answers dilute goodput exactly as they
        should. When the server backs up, per-connection pipelines and
        socket buffers fill and sends fall behind schedule — counted as
        ``late_sends``, the open-loop demand the collapsing server could
        not even absorb."""
        K = min(
            int(os.environ.get("BENCH_OVERLOAD_CONNS", "192")),
            max(1, len(schedule)),
        )
        conns = []
        for _ in range(K):
            s = socket.create_connection(
                ("127.0.0.1", http_port), timeout=60
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conns.append((s, s.makefile("rb", -1)))
        results = []
        res_lock = threading.Lock()
        late = [0, 0]  # late sends, never-sent

        def run_conn(ci, t0):
            s, rf = conns[ci]
            times = schedule[ci::K]
            sent = []  # send walltimes; appended BEFORE the matching read
            n_late = 0
            dead = threading.Event()

            def writer():
                nonlocal n_late
                for j, at in enumerate(times):
                    if dead.is_set():
                        return
                    delay = t0 + at - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    elif delay < -0.05:
                        n_late += 1
                    sent.append(time.perf_counter())
                    try:
                        s.sendall(request_bytes(ci + j * K, True))
                    except OSError:
                        sent.pop()
                        dead.set()
                        return

            wt = threading.Thread(target=writer, daemon=True)
            wt.start()
            local, got = [], 0
            try:
                for j in range(len(times)):
                    status_line = rf.readline(65537)
                    if not status_line:
                        break
                    parts = status_line.split(None, 2)
                    status = int(parts[1])
                    clen, close = 0, False
                    while True:
                        h = rf.readline(65537)
                        if h in (b"\r\n", b"\n", b""):
                            break
                        key, _, value = h.partition(b":")
                        key = key.strip().lower()
                        if key == b"content-length":
                            clen = int(value)
                        elif key == b"connection":
                            close = value.strip().lower() == b"close"
                    raw = rf.read(clen)
                    dt = (time.perf_counter() - sent[j]) * 1e3
                    if status == 200:
                        assert raw.startswith(b"[[") and not _has_zero_cell(
                            raw
                        ), "incomplete board from /solve"
                    local.append((status, dt))
                    got += 1
                    if close:
                        break
            except (OSError, ValueError):
                pass
            dead.set()
            wt.join()
            with res_lock:
                results.extend(local)
                # sent but never answered -> transport errors; scheduled
                # but never sent -> unsent (a dead conn's leftover slice)
                results.extend((0, None) for _ in range(len(sent) - got))
                late[0] += n_late
                late[1] += len(times) - len(sent)

        # one shared epoch, with enough grace for all K reader/writer
        # thread pairs to exist before the first scheduled arrival
        t0 = time.perf_counter() + 1.0
        threads = [
            threading.Thread(target=run_conn, args=(ci, t0))
            for ci in range(K)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        for s, rf in conns:
            try:
                rf.close()
                s.close()
            except OSError:
                pass
        ok = [ms for s, ms in results if s == 200]
        shed = sum(1 for s, _ in results if s == 429)
        errors = (
            sum(1 for s, _ in results if s not in (200, 429)) + late[1]
        )
        return ok, shed, errors, late[0], wall

    def poisson_schedule(rate, duration, seed=20260802):
        # same seed for both A/B phases: identical offered schedules
        n = max(8, int(rate * duration))
        return np.random.default_rng(seed).exponential(
            1.0 / rate, size=n
        ).cumsum()

    # phase 1+2: calibrate on the no-admission node, then overload it.
    # Capacity is calibrated with the SAME open-loop client topology the
    # A/B uses, not the cheap closed-loop probe: on a shared host the
    # sustainable rate includes the transport and generator overheads,
    # and "2x" an overstated capacity would really be 4-5x (measured —
    # the closed-loop keep-alive probe reads ~2.5x higher than the
    # conn-pipelined open-loop path can actually sustain)
    def baseline_run():
        probe = closed_loop(cal_clients, cal_secs)
        cal_ok, _, _, _, cal_wall = open_loop(
            poisson_schedule(probe, cal_secs, seed=20260801)
        )
        capacity = len(cal_ok) / cal_wall if cal_wall else 0.0
        if capacity <= 0:
            raise RuntimeError("calibration completed no requests")
        r = capacity * xmult
        return probe, capacity, r, open_loop(poisson_schedule(r, secs))

    probe_pps, cal_pps, rate, base_out = with_node([], baseline_run)
    base_ok, base_shed, base_errs, base_late, base_wall = base_out

    # phase 3: identical offered load against the admission node; the
    # pending budget is matched to the deadline (capacity × budget = the
    # backlog a deadline-meeting queue can hold, × 0.4 so service time
    # and client-side pipeline wait on top of a full queue still land
    # inside the budget — at 0.7 the admitted p50 sat at the deadline
    # edge and the p99 spilled past it, measured)
    adm_capacity = max(8, int(0.4 * cal_pps * deadline_ms / 1e3))
    adm_flags = [
        "--admission-capacity", str(adm_capacity),
        "--default-deadline-ms", str(deadline_ms),
        "--adaptive-coalesce",
    ]

    def adm_run():
        out = open_loop(poisson_schedule(rate, secs))
        metrics = {}
        try:
            metrics = scrape("/metrics")
        except Exception:
            pass
        return out, metrics

    (adm_ok, adm_shed, adm_errs, adm_late, adm_wall), adm_metrics = (
        with_node(adm_flags, adm_run)
    )

    def pct(vals, q):
        return round(float(np.percentile(np.asarray(vals), q)), 2) if vals else None

    # goodput = 200s answered WITHIN the deadline (both phases judged by
    # the same bar); raw completed pps rides along for context
    base_good = [ms for ms in base_ok if ms <= deadline_ms]
    adm_good = [ms for ms in adm_ok if ms <= deadline_ms]
    base_goodput = len(base_good) / base_wall if base_wall else 0.0
    adm_goodput = len(adm_good) / adm_wall if adm_wall else 0.0
    total = max(1, len(adm_ok) + adm_shed + adm_errs)
    admission_stats = adm_metrics.get("admission", {})
    record = {
        "metric": (
            f"overload_goodput_puzzles_per_sec_{xmult:g}x_{size}x{size}"
        ),
        "value": round(adm_goodput, 1),
        "unit": "puzzles/s",
        # admission goodput over the no-admission baseline's, identical
        # open-loop schedule (the A/B this mode exists for)
        "vs_baseline": round(adm_goodput / base_goodput, 3)
        if base_goodput
        else None,
        # the cheap keep-alive probe (engine-bound upper bound) and the
        # open-loop-topology capacity the offered rate is derived from
        "closed_loop_pps": round(probe_pps, 1),
        "calibrated_capacity_pps": round(cal_pps, 1),
        "offered_rps": round(rate, 1),
        # the ISSUE 2 acceptance ratio: >= 0.9 wanted (vs the sustainable
        # rate of the same serving topology the overload is offered to)
        "goodput_vs_closed_loop": round(adm_goodput / cal_pps, 3)
        if cal_pps
        else None,
        "shed_rate": round(adm_shed / total, 3),
        "completed_pps": round(len(adm_ok) / adm_wall, 1) if adm_wall else 0.0,
        "admitted_p50_ms": pct(adm_ok, 50),
        "admitted_p99_ms": pct(adm_ok, 99),
        "deadline_ms": deadline_ms,
        "admission_capacity": adm_capacity,
        "admission_errors": adm_errs,
        "admission_late_sends": adm_late,
        "server_shed_capacity": admission_stats.get("shed_capacity"),
        "server_shed_deadline": admission_stats.get("shed_deadline"),
        "server_expired": admission_stats.get("expired"),
        "baseline": {
            "goodput_pps": round(base_goodput, 1),
            "completed_pps": round(len(base_ok) / base_wall, 1)
            if base_wall
            else 0.0,
            "p50_ms": pct(base_ok, 50),
            "p99_ms": pct(base_ok, 99),
            "errors": base_errs,
            "late_sends": base_late,
            "wall_s": round(base_wall, 2),
        },
    }
    out_path = os.environ.get("BENCH_OVERLOAD_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1)
    print(json.dumps(record))
    print(
        f"# overload: probe={probe_pps:.1f}pps "
        f"cal={cal_pps:.1f}pps offered={rate:.1f}rps x{xmult:g} "
        f"secs={secs} deadline={deadline_ms}ms capacity={adm_capacity} | "
        f"baseline goodput={base_goodput:.1f}pps (completed "
        f"{len(base_ok) / base_wall:.1f}pps) p99={pct(base_ok, 99)}ms "
        f"errors={base_errs} late={base_late} wall={base_wall:.1f}s | "
        f"admission goodput={adm_goodput:.1f}pps (completed "
        f"{len(adm_ok) / adm_wall:.1f}pps) p99={pct(adm_ok, 99)}ms "
        f"shed={adm_shed}/{total} errors={adm_errs} late={adm_late} "
        f"wall={adm_wall:.1f}s (goodput = 200s within the deadline)",
        file=sys.stderr,
    )


def main_obs_overhead():
    """The tracing plane's cost proof + incident artifact (ISSUE 6).

    Phase A — overhead A/B: TWO nodes boot side by side — the default
    tracing-on stack and a ``--no-obs`` baseline — and BENCH_OBS_CLIENTS
    (default 64) closed-loop keep-alive clients drive them in short
    alternating windows (BENCH_OBS_WINDOWS pairs of BENCH_OBS_SECS,
    defaults 24 x 2 s), flipping which arm goes first every pair.
    Design notes, all measured on this class of shared host: available
    CPU swings ~2x on a seconds timescale (cgroup burst/throttle
    cycles), and the SECOND of two back-to-back windows loses up to 40%
    regardless of arm — so windows are short, many, and order-balanced,
    and the headline is the MEDIAN of per-pair on/off ratios. Driving
    both nodes concurrently instead would be weather-free but measures
    the wrong thing (two processes competing for the same cores punish
    the heavier arm super-linearly — a co-residency scenario, not
    "a traced node vs itself untraced"). The artifact also carries
    ``cpu_us_per_request`` per arm from /proc/<pid> accounting — a
    second view of the same claim (less weather-proof than it looks:
    CPU-seconds stretch under frequency throttling, so it has read
    +35..+135 us across runs against an isolated tracer cost of
    ~14 us/request — microbenched — plus allocation/GC amortization).
    Acceptance wants ≥0.97 (vs_baseline normalizes to it).

    Phase B — incident: in-process engine + supervisor + flight recorder
    with a POISONED bucket (utils/faults.EngineFaultInjector.corrupt):
    one traced /solve-shaped request gets a silently-wrong device answer,
    host verification catches it, the breaker trips DEGRADED, and the
    flight recorder's incident dump must contain that very request's span
    with per-stage timings (queue/coalesce/device/verify + fallback) —
    the black box demonstrably answers "what was the node doing when it
    went DEGRADED".

    Artifact: benchmarks/obs_overhead_pr10.json (BENCH_OBS_OUT
    overrides; obs_overhead_pr6.json is the frozen PR 6 baseline the
    refreshed paired ratio is compared against — the ISSUE 10 cost
    accounting records per batch on the same serving path and must not
    regress the bound). Default platform cpu (same pooled-chip rule as
    farm/concurrent).
    """
    import subprocess
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch

    clients = int(os.environ.get("BENCH_OBS_CLIENTS", "64"))
    secs = float(os.environ.get("BENCH_OBS_SECS", "2"))
    windows = int(os.environ.get("BENCH_OBS_WINDOWS", "24"))
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    repo = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "BENCH_OBS_OUT",
        os.path.join(repo, "benchmarks", "obs_overhead_pr10.json"),
    )
    base_port = 18400 + os.getpid() % 700
    PORT_ON, PORT_OFF = base_port, base_port + 2

    hard = os.path.join(repo, "benchmarks", "corpus_9x9_hard_64.npz")
    if os.path.exists(hard):
        boards = np.load(hard)["boards"][:32]
    else:
        boards = generate_batch(32, 64, seed=20260802, unique=True)
    bodies = [json.dumps({"sudoku": b.tolist()}).encode() for b in boards]

    # Resource isolation, same rationale as --mode overload: on a shared
    # small host an unpinned server + 64 generator threads find different
    # GIL/scheduler equilibria per boot (measured: per-phase pps varying
    # 2x with zero ambient load), which drowns a few-percent overhead
    # A/B. One dedicated core per role makes phases repeatable.
    cores = (
        sorted(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity")
        else []
    )
    node_prefix = []
    if (
        len(cores) >= 2
        and platform == "cpu"
        and os.environ.get("BENCH_OBS_NO_PIN") != "1"
        and __import__("shutil").which("taskset") is not None
    ):
        node_prefix = ["taskset", "-c", str(cores[0])]
        os.sched_setaffinity(0, set(cores[1:]))

    import socket

    requests_bytes = [
        b"POST /solve HTTP/1.1\r\nHost: bench\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: %d\r\n\r\n%s" % (len(b), b)
        for b in bodies
    ]

    class RawConn:
        """Keep-alive raw-socket client (the main_concurrent shape: the
        load generator must not out-cost the thing being measured)."""

        def __init__(self, port, timeout=300.0):
            self.port = port
            self.timeout = timeout
            self.sock = None
            self.rf = None

        def _connect(self):
            self.sock = socket.create_connection(
                ("127.0.0.1", self.port), timeout=self.timeout
            )
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.rf = self.sock.makefile("rb", -1)

        def close(self):
            if self.sock is not None:
                try:
                    self.rf.close()
                    self.sock.close()
                except OSError:
                    pass
            self.sock = self.rf = None

        def post(self, k):
            if self.sock is None:
                self._connect()
            t0 = time.perf_counter()
            self.sock.sendall(requests_bytes[k % len(requests_bytes)])
            status_line = self.rf.readline(65537)
            if not status_line:
                raise OSError("server closed connection")
            parts = status_line.split(None, 2)
            clen, close = 0, False
            while True:
                h = self.rf.readline(65537)
                if h in (b"\r\n", b"\n", b""):
                    break
                key, _, value = h.partition(b":")
                key = key.strip().lower()
                if key == b"content-length":
                    clen = int(value)
                elif key == b"connection":
                    close = value.strip().lower() == b"close"
            raw = self.rf.read(clen)
            dt = (time.perf_counter() - t0) * 1e3
            if close:
                self.close()
            assert parts[1] == b"200", (
                f"/solve answered {parts[1]!r}: {raw[:120]!r}"
            )
            return dt

    def scrape(port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5
        ) as r:
            return r.headers, r.read()

    def boot_node(http_port, udp_port, extra_flags):
        return subprocess.Popen(
            node_prefix
            + [
                sys.executable, os.path.join(repo, "node.py"),
                "-p", str(http_port), "-s", str(udp_port), "-h", "0",
                "--serving-stats", "--metrics", "--buckets", "1,8,64",
                # the A/B isolates the TRACING plane's overhead: cached
                # answers would skip the stages being measured
                "--no-answer-cache",
            ]
            + (["--coalesce-max-batch", "8"] if platform == "cpu" else [])
            + (["--platform", platform] if platform else [])
            + extra_flags,
            cwd=repo,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    def wait_ready(proc, port, deadline):
        while True:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"node exited rc={proc.returncode} before serving"
                )
            try:
                scrape(port, "/stats")
                break
            except Exception:
                if time.time() > deadline:
                    raise RuntimeError("node did not come up") from None
                time.sleep(0.5)
        while time.time() < deadline:
            _h, raw = scrape(port, "/metrics")
            eng_m = json.loads(raw).get("engine", {})
            if eng_m.get("fully_warmed", eng_m.get("warmed")):
                break
            time.sleep(0.5)
        else:
            raise RuntimeError("engine warmup did not finish")
        c = RawConn(port)
        fast = 0
        while fast < 2 and time.time() < deadline:
            fast = fast + 1 if c.post(0) < 500 else 0
        c.close()

    def drive(port):
        """One closed-loop measurement window against ``port``; clients
        keep their connections across windows (conns dict below) so a
        window measures serving, not reconnect storms."""
        stop = time.perf_counter() + secs
        counts, failures = [], []
        lock = threading.Lock()

        def client(i):
            conn, n, k = conns.setdefault((port, i), RawConn(port)), 0, i
            try:
                while time.perf_counter() < stop:
                    try:
                        conn.post(k)
                        n += 1
                    except AssertionError as e:
                        failures.append(f"client {i}: {e}")
                        return
                    except OSError:
                        conn.close()
                    k += clients
            finally:
                with lock:
                    counts.append(n)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert not failures, failures[:3]
        return sum(counts), wall

    def cpu_s(pid):
        """The node process's accumulated CPU seconds (utime+stime)."""
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().split()
        return (int(parts[13]) + int(parts[14])) / os.sysconf("SC_CLK_TCK")

    conns = {}
    phases = {"off": [], "on": []}
    totals = {"off": [0, 0.0], "on": [0, 0.0]}
    cpu = {"off": [0.0, 0], "on": [0.0, 0]}  # cpu seconds, requests
    timing_sample = None
    obs_snapshot = None
    cost_snapshot = None
    proc_on = boot_node(PORT_ON, PORT_ON - 1000, [])
    proc_off = boot_node(PORT_OFF, PORT_OFF - 1000, ["--no-obs"])
    arm_proc = {"on": proc_on, "off": proc_off}
    try:
        deadline = time.time() + 240
        wait_ready(proc_on, PORT_ON, deadline)
        wait_ready(proc_off, PORT_OFF, deadline)
        def arm_window(arm, port):
            def run():
                c0 = cpu_s(arm_proc[arm].pid)
                n, wall = drive(port)
                cpu[arm][0] += cpu_s(arm_proc[arm].pid) - c0
                cpu[arm][1] += n
                pps = n / wall
                phases[arm].append(round(pps, 1))
                totals[arm][0] += n
                totals[arm][1] += wall
                return pps

            return run

        # order-flipped paired windows + median-of-ratios headline via
        # the shared helper (run_paired_windows — the third copy of this
        # logic is gone; see --mode hotloop / --mode continuous)
        _rows, paired, median_paired = run_paired_windows(
            [
                ("off", arm_window("off", PORT_OFF)),
                ("on", arm_window("on", PORT_ON)),
            ],
            max(1, windows),
            ratio_of=("on", "off"),
        )
        # one opt-in X-Timing request proves the header end to end
        req = urllib.request.Request(
            f"http://127.0.0.1:{PORT_ON}/solve",
            data=bodies[0],
            headers={"X-Timing": "1", "X-Request-Id": "bench-obs-probe"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            timing_sample = json.loads(r.headers["X-Timing"])
            assert r.headers["X-Request-Id"] == "bench-obs-probe"
        _h, raw = scrape(PORT_ON, "/metrics")
        metrics_body = json.loads(raw)
        obs_snapshot = metrics_body.get("obs", {})
        # the ISSUE 10 cost-accounting evidence from the driven node
        # itself: per-bucket device-seconds / fill / lane utilization
        # recorded on the SERVING path during the A/B windows
        cost_snapshot = metrics_body.get("engine", {}).get("cost")
    finally:
        for c in conns.values():
            c.close()
        for proc in (proc_on, proc_off):
            proc.terminate()
        for proc in (proc_on, proc_off):
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
    on_pps = totals["on"][0] / totals["on"][1]
    off_pps = totals["off"][0] / totals["off"][1]
    ratio = on_pps / off_pps if off_pps else 0.0
    cpu_us = {
        arm: round(c / n * 1e6, 1) if n else None
        for arm, (c, n) in cpu.items()
    }
    # the off-arm's own spread: the reader's noise gauge for a shared box
    off_spread = (
        round(max(phases["off"]) / min(phases["off"]), 3)
        if min(phases["off"]) > 0
        else None
    )

    # -- phase B: the injected breaker-trip incident -----------------------
    import jax

    jax.config.update("jax_platforms", platform or "cpu")
    from sudoku_solver_distributed_tpu.engine import SolverEngine
    from sudoku_solver_distributed_tpu.obs import FlightRecorder, Tracer
    from sudoku_solver_distributed_tpu.serving.health import EngineSupervisor
    from sudoku_solver_distributed_tpu.utils import EngineFaultInjector

    dump_dir = tempfile.mkdtemp(prefix="obs_incident_")
    eng = SolverEngine(buckets=(1, 4), coalesce=True)
    eng.warmup()
    flight = FlightRecorder(dump_dir=dump_dir, incident_delay_s=0.2)
    tracer = Tracer(recorder=flight)
    inj = EngineFaultInjector()
    eng.fault_injector = inj
    sup = EngineSupervisor(eng, probe_interval_s=600.0)
    flight.attach_supervisor(sup)
    incident = {}
    try:
        board = [[0] * 9 for _ in range(9)]
        board[0][0] = 5
        # warm span first (healthy), then poison the width-1 program:
        # the next traced request's device answer is silently wrong, host
        # verification catches it, breaker trips, flight record dumps
        t = tracer.start("/solve")
        eng.solve_one_supervised(board)
        tracer.finish(t, 200)
        # poison both widths the coalesced path may dispatch at: the
        # continuous segment driver (PR 12 default) runs its lane pool at
        # the bucket covering the batch cap (4 here); the closed-loop arm
        # would dispatch the lone request at width 1
        inj.poison_bucket(1)
        inj.poison_bucket(4)
        t = tracer.start("/solve")
        sol, info = eng.solve_one_supervised(board)
        tracer.finish(t, 200, degraded=bool(info.get("degraded")))
        assert sol is not None, "fallback failed to answer"
        deadline = time.time() + 10
        while flight.stats()["dumps"] == 0 and time.time() < deadline:
            time.sleep(0.05)
        record_path = flight.stats()["last_dump_path"]
        assert record_path, "incident dump never landed"
        with open(record_path) as f:
            payload = json.load(f)
        poisoned = [s for s in payload["spans"] if s.get("fallback")]
        assert poisoned, "poisoned request's span missing from the dump"
        span = poisoned[-1]
        for k in ("queue_ms", "coalesce_ms", "device_ms", "verify_ms"):
            assert k in span, f"span missing stage {k}"
        incident = {
            "reason": payload["reason"],
            "spans_in_dump": len(payload["spans"]),
            "events": payload["events"],
            "poisoned_span": span,
        }
    finally:
        sup.close()
        eng.fault_injector = None
        eng.close()

    record = {
        "metric": f"obs_overhead_throughput_ratio_{clients}c_9x9",
        # median paired-window ratio (see docstring: robust to episodic
        # single-window scheduler stalls; the aggregate rides below)
        "value": round(median_paired, 4),
        "unit": "x_tracing_on_vs_off",
        # acceptance bar: tracing-on >= 0.97x tracing-off (>=1.0 meets it)
        "vs_baseline": round(median_paired / 0.97, 3),
        "aggregate_ratio": round(ratio, 4),
        "clients": clients,
        "window_secs": secs,
        "windows": windows,
        "platform": platform,
        "tracing_on_pps": round(on_pps, 1),
        "tracing_off_pps": round(off_pps, 1),
        "phases": phases,
        "paired_ratios_sorted": paired,
        "median_paired_ratio": median_paired,
        "off_phase_spread": off_spread,
        # the weather-resistant view: server CPU per request per arm
        # (/proc accounting) — the tracing plane's cost as CPU, immune to
        # the throughput lottery a small shared host plays
        "cpu_us_per_request": cpu_us,
        "cpu_overhead_ratio": (
            round(cpu_us["on"] / cpu_us["off"], 4)
            if cpu_us["on"] and cpu_us["off"]
            else None
        ),
        "timing_header_sample": timing_sample,
        "obs_snapshot": obs_snapshot,
        "engine_cost": cost_snapshot,
        "incident": incident,
    }
    # the paired-ratio bound this refresh must hold: PR 6's committed
    # artifact (tracing plane alone) — cost accounting rides the same
    # serving path and records per batch, so the ratio must not regress
    pr6_path = os.path.join(repo, "benchmarks", "obs_overhead_pr6.json")
    if os.path.exists(pr6_path):
        with open(pr6_path) as f:
            record["pr6_value"] = json.load(f).get("value")
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    headline = {k: record[k] for k in ("metric", "value", "unit", "vs_baseline")}
    print(json.dumps(headline))
    print(
        f"# obs-overhead: on={on_pps:.1f}pps off={off_pps:.1f}pps "
        f"ratio={ratio:.4f} median_paired={median_paired} "
        f"off_spread={off_spread} cpu_us/req={cpu_us} clients={clients} "
        f"windows={windows}x{secs}s "
        f"| incident: {incident.get('reason')} "
        f"spans={incident.get('spans_in_dump')} "
        f"poisoned_span stages="
        f"{ {k: incident['poisoned_span'][k] for k in ('queue_ms', 'coalesce_ms', 'device_ms', 'verify_ms', 'fallback_ms')} if incident else None} "
        f"| artifact: {out_path}",
        file=sys.stderr,
    )


def main_hotloop():
    """In-jit hot-loop A/B (ISSUE 7): the PR 7 solver loop vs the legacy loop.

    Two jitted arms of the SAME corpus in ONE pinned process:

      * ``default`` — the shipping loop: dense div-2/floor-16 compaction
        ladder with prefix-gather level boundaries, one-hot step merges,
        packed bitplane locked-candidate analysis (ops/config.COMPACTION /
        PACKED_DEFAULT);
      * ``legacy`` — ``solve_batch(..., legacy_loop=True)``: the pre-PR7
        loop end to end (quartering floor-64 ladder, full-permute
        boundaries, scatter merges, unpacked analysis).

    Measurement discipline matches overload_pr2.json / obs_overhead_pr6:
    the process pins itself to one core, windows are short and paired with
    the arm order flipped every pair (this host's available CPU swings ~2x
    on a seconds timescale), and the headline ratio is the MEDIAN of
    per-pair legacy/default time ratios. Each window is sustained
    throughput: back-to-back async dispatches, one trailing sync — the
    saturated-engine shape the throughput mode measures.

    Counter proof (machine-independent): both arms run with
    ``return_stats=True`` — ``lane_steps`` (board-lanes swept) and
    ``idle_lane_steps`` (lanes swept after their board already finished).
    A dedicated straggler phase solves a batch of easy boards plus ONE
    deep board: each arm's tail pays its ladder floor minus one in
    finished lanes per iteration — ~63 for the legacy quartering
    floor-64 ladder, under 16 for the dense floor-16 ladder (~4× less;
    an UNCOMPACTED full-batch loop would pay ~B-1 ≈ 4095). "Finished
    boards stop iterating" concretely: 15-ish finished-lane sweeps per
    tail iteration out of a 4096 batch, ~0.4% of B.

    Artifact: benchmarks/hotloop_pr7.json (BENCH_HOTLOOP_OUT overrides);
    stdout carries the usual one-line JSON (value = default-arm sustained
    puzzles/s, vs_baseline = median paired speedup vs legacy).
    ``--smoke`` (or BENCH_HOTLOOP_SMOKE=1): committed 64-board corpus,
    2 pairs, 1 solve per window — the CI plumbing check.
    """
    smoke = (
        "--smoke" in sys.argv[1:]
        or os.environ.get("BENCH_HOTLOOP_SMOKE") == "1"
    )
    import statistics

    import jax

    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    if platform:
        jax.config.update("jax_platforms", platform)

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch
    from sudoku_solver_distributed_tpu.ops import (
        cpu_serving_config,
        serving_config,
        solve_batch,
        spec_for_size,
    )
    from sudoku_solver_distributed_tpu.ops.config import (
        SOLVER_PRESETS,
        compaction_config,
        packed_default,
    )

    size = int(os.environ.get("BENCH_SIZE", "9"))
    spec = spec_for_size(size)
    repo = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "BENCH_HOTLOOP_OUT",
        os.path.join(repo, "benchmarks", "hotloop_pr7.json"),
    )
    pairs = int(os.environ.get("BENCH_HOTLOOP_PAIRS", "2" if smoke else "8"))
    per_window = int(
        os.environ.get("BENCH_HOTLOOP_WINDOW_SOLVES", "1" if smoke else "3")
    )

    # pin to one core (the overload_pr2 discipline): an unpinned process on
    # a 2-core shared host migrates mid-window and the A/B drowns in
    # scheduler noise. The paired-window median tolerates what remains.
    pinned = False
    if hasattr(os, "sched_setaffinity") and platform == "cpu":
        try:
            cores = sorted(os.sched_getaffinity(0))
            os.sched_setaffinity(0, {cores[0]})
            pinned = True
        except OSError:
            pass

    if smoke:
        corpus_file = os.path.join(
            repo, "benchmarks", f"corpus_{size}x{size}_hard_64.npz"
        )
        boards = (
            np.load(corpus_file)["boards"]
            if os.path.exists(corpus_file)
            else generate_batch(64, 64, size=size, seed=20260729, unique=True)
        )
    else:
        corpus_file = os.path.join(
            repo, "benchmarks", f"corpus_{size}x{size}_hard_4096.npz"
        )
        boards = np.load(corpus_file)["boards"]
    B = boards.shape[0]
    dev = jnp.asarray(boards)
    cfg = cpu_serving_config(size) if platform == "cpu" else serving_config(size)

    # the A/B arms ARE the --solver-config presets (ops/config.py, the
    # single definition site): the bench provably measures what
    # `node.py --solver-config legacy` would serve
    arms = {
        "default": dict(SOLVER_PRESETS["default"]),
        "legacy": dict(SOLVER_PRESETS["legacy"]),
    }
    fns, counters, grids = {}, {}, {}
    for name, kw in arms.items():
        fns[name] = jax.jit(
            lambda g, kw=kw: solve_batch(
                g, spec, return_stats=True, **cfg, **kw
            )
        )
        res, st = jax.block_until_ready(fns[name](dev))
        assert bool(np.asarray(res.solved).all()), f"{name}: unsolved boards"
        counters[name] = {
            "iters": int(res.iters),
            "guesses": int(np.asarray(res.guesses).sum()),
            "validations": int(np.asarray(res.validations).sum()),
            "lane_steps": int(st.lane_steps),
            "idle_lane_steps": int(st.idle_lane_steps),
            "idle_fraction": round(
                int(st.idle_lane_steps) / max(1, int(st.lane_steps)), 4
            ),
        }
        grids[name] = np.asarray(res.grid)
    # the A/B is only meaningful if both arms solve identically
    np.testing.assert_array_equal(grids["default"], grids["legacy"])

    def window(fn):
        t0 = time.perf_counter()
        outs = [fn(dev) for _ in range(per_window)]
        jax.block_until_ready(outs[-1])
        return (time.perf_counter() - t0) / per_window

    # order-flipped paired windows, median-of-ratios headline: the shared
    # discipline (run_paired_windows — one definition with obs-overhead
    # and --mode continuous)
    rows, _ratios, ratio = run_paired_windows(
        [
            ("default", lambda: window(fns["default"])),
            ("legacy", lambda: window(fns["legacy"])),
        ],
        pairs,
        ratio_of=("legacy", "default"),
    )
    pair_rows = [
        {
            "order": r["order"],
            "default_s": r["default"],
            "legacy_s": r["legacy"],
            "ratio": r["ratio"],
        }
        for r in rows
    ]
    default_pps = B / statistics.median(r["default_s"] for r in pair_rows)
    legacy_pps = B / statistics.median(r["legacy_s"] for r in pair_rows)

    # --- straggler phase: one DEEP board among easy ones -----------------
    # The "finished boards stop iterating" proof: the deep-mined straggler
    # runs a ~5.5k-iteration tail after the easy mass finishes within ~10
    # iterations, so the whole-solve idle-lanes-per-iteration average
    # converges to the tail's steady state: each arm sweeps its ladder
    # floor minus one in finished lanes per tail iteration — ~63 for the
    # legacy floor-64 ladder vs <16 for the dense floor-16 ladder (an
    # uncompacted loop would sweep all B-1 ≈ 4095).
    straggler = None
    if size == 9:
        sb = 64 if smoke else 4096
        easy = generate_batch(sb - 1, 30, seed=20260803)  # singles-solvable
        deep_path = os.path.join(
            repo, "benchmarks", "corpus_9x9_deep_union.npz"
        )
        deep = (
            np.load(deep_path)["boards"][:1]
            if os.path.exists(deep_path)
            else generate_batch(1, 64, seed=7, unique=True)
        )
        batch = jnp.asarray(np.concatenate([easy, deep], axis=0))
        straggler = {"batch": sb, "straggler": "corpus_9x9_deep_union[0]"}
        for name, kw in arms.items():
            # the deep corpus exceeds the serving 4096-iteration budget
            # (the engine's deep retry covers that in serving): a
            # dedicated program with the deep-retry headroom
            f = jax.jit(
                lambda g, kw=kw: solve_batch(
                    g, spec, return_stats=True,
                    **{**cfg, "max_iters": 65536}, **kw,
                )
            )
            res, st = jax.block_until_ready(f(batch))
            assert bool(np.asarray(res.solved).all())
            iters = int(res.iters)
            straggler[name] = {
                "iters": iters,
                "lane_steps": int(st.lane_steps),
                "idle_lane_steps": int(st.idle_lane_steps),
                "idle_lanes_per_iter": round(
                    int(st.idle_lane_steps) / max(1, iters), 1
                ),
            }
        floor = compaction_config(size)["floor"]
        straggler["compact_floor"] = floor
        # the compacted loop's tail sweeps fewer finished lanes per
        # iteration than the ladder floor (+1 headroom for the pre-
        # compaction transition's contribution to the average)
        straggler["post_compaction_idle_ok"] = bool(
            straggler["default"]["idle_lanes_per_iter"] < floor + 1
        )

    record = {
        "metric": f"hotloop_sustained_puzzles_per_sec_{size}x{size}",
        "value": round(default_pps, 1),
        "unit": "puzzles/s",
        "vs_baseline": round(ratio, 4),
        "legacy_pps": round(legacy_pps, 1),
        "batch": B,
        "corpus": os.path.basename(corpus_file),
        "platform": platform or "default",
        "pinned_core": pinned,
        "pairs": pair_rows,
        "window_solves": per_window,
        "config": {
            **cfg,
            "packed_default": packed_default(size),
            "compaction": compaction_config(size),
        },
        "counters": counters,
        "straggler": straggler,
        "smoke": smoke,
    }
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    headline = {
        k: record[k] for k in ("metric", "value", "unit", "vs_baseline")
    }
    print(json.dumps(headline))
    print(
        f"# hotloop: default={default_pps:.0f}pps legacy={legacy_pps:.0f}pps "
        f"median_paired_ratio={ratio:.3f} batch={B} pinned={pinned} "
        f"idle_frac {counters['default']['idle_fraction']} vs "
        f"{counters['legacy']['idle_fraction']} "
        f"| straggler idle/iter "
        f"{straggler['default']['idle_lanes_per_iter'] if straggler else 'n/a'} vs "
        f"{straggler['legacy']['idle_lanes_per_iter'] if straggler else 'n/a'} "
        f"| artifact: {out_path}",
        file=sys.stderr,
    )


def main_continuous():
    """Continuous-batching pipelined-boundary A/B (ISSUE 15): the PR 15
    pipelined segment boundary — buffer donation, digest-only two-phase
    fetch, dispatch-before-resolve + one-deep speculation + injection
    pre-staging — vs the PR 12 boundary (``--no-segment-pipeline``:
    full-row fetch, no donation, strictly serial boundaries), under an
    OPEN-LOOP Poisson load at BENCH_CONTINUOUS_X (default 2×) the
    measured baseline capacity, on a mixed easy/deep request pool —
    the straggler-tail traffic where boundary overhead dominates.

    Both arms replay the IDENTICAL arrival schedule in order-flipped
    paired windows (run_paired_windows — the shared discipline with
    hotloop/obs-overhead). Per window:

      * sustained pps — the window's resolved-board delta from the
        engine.cost continuous counters over the window wall (the
        device-side truth; the headline paired ratio, acceptance
        ≥ 1.10× for the pipelined arm);
      * boundary evidence — windowed deltas of ``boundary_host_ms``
        (the fetch-done→next-dispatch host gap, the span the pipeline
        exists to close) and ``fetch_bytes`` per segment (the digest
        cut);
      * deadline-conditioned p99/p50 + goodput over ANSWERED requests,
        and sustained lane utilization, as the PR 12 bench measured.

    Parity gate: every answered solution must equal the closed-loop
    batch reference bit-for-bit, and the artifact carries per-arm
    sha256 hashes over the (window, request, solution) stream of
    requests answered in BOTH arms — equal hashes = bit-identical
    answers under donation + digest-only boundaries. Golden search
    counters (guesses/validations per answer) ride the same rows.

    Off-smoke, a 25×25 probe runs ONE real digest-program boundary at
    width 4 and records measured digest bytes vs the full-row fetch —
    the ~80× boundary-byte cut at scale.

    Artifact: benchmarks/pipeline_pr15.json (BENCH_CONTINUOUS_OUT
    overrides). ``--smoke`` (or BENCH_CONTINUOUS_SMOKE=1): short windows
    for CI plumbing.
    """
    smoke = (
        "--smoke" in sys.argv[1:]
        or os.environ.get("BENCH_CONTINUOUS_SMOKE") == "1"
    )
    import hashlib
    import statistics
    import threading

    import jax

    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    if platform:
        jax.config.update("jax_platforms", platform)

    import numpy as np

    from sudoku_solver_distributed_tpu.engine import SolverEngine
    from sudoku_solver_distributed_tpu.models import generate_batch
    from sudoku_solver_distributed_tpu.serving.admission import (
        DeadlineExceeded,
    )

    repo = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "BENCH_CONTINUOUS_OUT",
        os.path.join(repo, "benchmarks", "pipeline_pr15.json"),
    )
    # short/many/order-flipped windows — the obs-overhead discipline:
    # this class of host swings ~2× on a seconds timescale (burst/
    # throttle cycles), so many 2 s paired windows beat few 6 s ones
    pairs = int(
        os.environ.get("BENCH_CONTINUOUS_PAIRS", "2" if smoke else "12")
    )
    secs = float(
        os.environ.get("BENCH_CONTINUOUS_SECS", "1.5" if smoke else "2")
    )
    over_x = float(os.environ.get("BENCH_CONTINUOUS_X", "2"))
    deadline_ms = float(
        os.environ.get("BENCH_CONTINUOUS_DEADLINE_MS", "400")
    )

    # pin to one core on CPU (the hotloop/overload discipline): the A/B
    # must not drown in scheduler migration noise on a small shared host
    pinned = False
    if hasattr(os, "sched_setaffinity") and platform == "cpu":
        try:
            cores = sorted(os.sched_getaffinity(0))
            os.sched_setaffinity(0, {cores[0]})
            pinned = True
        except OSError:
            pass

    # Mixed easy/deep pool: 3/4 singles-solvable easy mass + the committed
    # hard corpus as the deep tail, shuffled with a fixed seed so both
    # arms (and every rerun) see the identical request stream.
    hard_path = os.path.join(repo, "benchmarks", "corpus_9x9_hard_64.npz")
    hard = (
        np.load(hard_path)["boards"]
        if os.path.exists(hard_path)
        else generate_batch(64, 64, seed=20260729, unique=True)
    )
    easy = generate_batch(192, 30, seed=20260804)
    pool = np.concatenate([easy, hard], axis=0)
    pool = pool[np.random.default_rng(20260804).permutation(len(pool))]

    # the parity reference: the pool solved once through the closed-loop
    # batch path — every answered open-loop request must match its row
    ref_eng = SolverEngine(buckets=(8,), coalesce=False, continuous=False)
    ref_solutions, ref_mask, _ = ref_eng.solve_batch_np(pool)
    assert bool(ref_mask.all()), "parity reference failed to solve the pool"
    ref_hash = hashlib.sha256(
        np.ascontiguousarray(ref_solutions, np.int32).tobytes()
    ).hexdigest()

    def make_engine(pipeline):
        kw = dict(
            buckets=(1, 8), coalesce_max_batch=8, continuous=True,
            segment_pipeline=pipeline,
        )
        seg = os.environ.get("BENCH_CONTINUOUS_SEGMENT_ITERS")
        if seg:
            kw["segment_iters"] = int(seg)
        # the long-job lane cap (ISSUE 13 satellite): sweeps the
        # deep-heavy goodput trade the PR 12 artifact recorded —
        # e.g. BENCH_CONTINUOUS_DEEP_LANE_CAP=2 bounds deep residents
        # to 2 of the pool's lanes under demand
        cap = os.environ.get("BENCH_CONTINUOUS_DEEP_LANE_CAP")
        if cap:
            kw["deep_lane_cap"] = int(cap)
        eng = SolverEngine(**kw)
        eng.warmup()
        return eng

    engines = {
        "pipelined": make_engine(True),
        "nopipeline": make_engine(False),
    }

    # closed-loop capacity of the BASELINE arm sets the open-loop rate
    def measure_capacity(eng, warm_s=1.5, clients=8):
        stop = time.monotonic() + warm_s
        counts = [0] * clients

        def client(i):
            while time.monotonic() < stop:
                sol, _ = eng.solve_one(
                    pool[(i * 31 + counts[i]) % len(pool)].tolist()
                )
                assert sol is not None
                counts[i] += 1

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / warm_s

    capacity = measure_capacity(engines["nopipeline"])
    rate = max(10.0, over_x * capacity)

    # ONE Poisson schedule, replayed identically by every window/arm
    sched_rng = np.random.default_rng(20260805)
    arrivals = []
    t = 0.0
    seq = 0
    while t < secs:
        arrivals.append((t, seq))
        t += float(sched_rng.exponential(1.0 / rate))
        seq += 1

    answered_by_arm = {"pipelined": {}, "nopipeline": {}}
    window_stats = {"pipelined": [], "nopipeline": []}
    window_idx = {"n": 0}

    def drive(arm):
        """Replay the schedule open-loop against one arm; returns the
        window's sustained pps (the paired measure) and appends the
        full stat row."""
        eng = engines[arm]
        w = window_idx["n"]
        window_idx["n"] += 1
        c0 = eng.cost.snapshot()
        lock = threading.Lock()
        lats, shed, failed = [], [0], [0]
        futs = []
        t0 = time.monotonic()
        for dt, s in arrivals:
            target = t0 + dt
            now = time.monotonic()
            if target > now:
                time.sleep(target - now)
            t_sub = time.monotonic()
            fut = eng.solve_one_async(
                pool[s % len(pool)].tolist(),
                deadline_s=t_sub + deadline_ms / 1e3,
            )

            def on_done(f, s=s, t_sub=t_sub, w=w):
                t_done = time.monotonic()
                try:
                    sol, _info = f.result()
                except DeadlineExceeded:
                    with lock:
                        shed[0] += 1
                    return
                except Exception:  # noqa: BLE001 — counted, not fatal
                    with lock:
                        failed[0] += 1
                    return
                with lock:
                    lats.append(t_done - t_sub)
                    # pair index (w//2): both arms of a pair replay the
                    # same schedule, so (pair, seq) names one request
                    answered_by_arm[arm][(w // 2, s)] = (
                        None
                        if sol is None
                        else np.asarray(sol, np.int32).tobytes()
                    )
                    if sol is not None and not np.array_equal(
                        np.asarray(sol, np.int32), ref_solutions[s % len(pool)]
                    ):
                        failed[0] += 10**6  # parity violation — loud

            fut.add_done_callback(on_done)
            futs.append(fut)
        for f in futs:
            try:
                f.result(timeout=120)
            except Exception:  # noqa: BLE001 — already counted
                pass
        wall = time.monotonic() - t0
        c1 = eng.cost.snapshot()
        dlane = c1["lane_steps"] - c0["lane_steps"]
        didle = c1["idle_lane_steps"] - c0["idle_lane_steps"]
        util = 100.0 * (dlane - didle) / dlane if dlane else 0.0
        # windowed deltas of the continuous block: resolved boards per
        # wall second (the headline), boundary host ms per segment, and
        # fetched bytes per segment (the digest-cut evidence)
        s0 = c0.get("continuous") or {}
        s1 = c1.get("continuous") or {}
        dseg = s1.get("segments", 0) - s0.get("segments", 0)
        dresolved = s1.get("resolved", 0) - s0.get("resolved", 0)
        dfetch = s1.get("fetch_bytes", 0) - s0.get("fetch_bytes", 0)
        # boundary_host_ms is a lifetime avg: recover the summed span
        dbh_ms = s1.get("boundary_host_ms", 0.0) * s1.get(
            "segments", 0
        ) - s0.get("boundary_host_ms", 0.0) * s0.get("segments", 0)
        sustained_pps = dresolved / wall if wall else 0.0
        lat_sorted = sorted(lats)

        def pct(q):
            return (
                round(lat_sorted[int(q * (len(lat_sorted) - 1))] * 1e3, 2)
                if lat_sorted
                else 0.0
            )

        row = {
            "arm": arm,
            "answered": len(lats),
            "shed": shed[0],
            "failed": failed[0],
            "goodput_pps": round(len(lats) / wall, 1),
            "sustained_pps": round(sustained_pps, 1),
            "util_pct": round(util, 2),
            "segments": dseg,
            "boundary_host_ms_per_segment": (
                round(dbh_ms / dseg, 4) if dseg else 0.0
            ),
            "fetch_bytes_per_segment": (
                round(dfetch / dseg, 1) if dseg else 0.0
            ),
            "p50_ms": pct(0.50),
            "p99_ms": pct(0.99),
        }
        window_stats[arm].append(row)
        return sustained_pps

    rows, ratios, pps_ratio = run_paired_windows(
        [
            ("pipelined", lambda: drive("pipelined")),
            ("nopipeline", lambda: drive("nopipeline")),
        ],
        pairs,
        ratio_of=("pipelined", "nopipeline"),
    )

    seg_iters = engines["pipelined"].segment_iters
    # end-state cost-plane evidence per arm (lifetime gauges)
    cost_evidence = {}
    for arm, eng in engines.items():
        snap = eng.cost.snapshot().get("continuous") or {}
        cost_evidence[arm] = {
            k: snap.get(k)
            for k in (
                "segments", "resolved", "pipelined", "fetch_bytes",
                "boundary_host_ms", "sustained_pipeline_depth",
            )
        }
        st = eng.coalescer.stats()
        cost_evidence[arm]["prestage_hits"] = st.get("prestage_hits", 0)
        cost_evidence[arm]["prestage_misses"] = st.get(
            "prestage_misses", 0
        )
        cost_evidence[arm]["deep_evictions"] = st.get(
            "deep_evictions", 0
        )
    for eng in engines.values():
        eng.close()
    ref_eng.close()

    # parity hashes over the requests answered in BOTH arms: equal hashes
    # = bit-identical answers under donation + digest-only boundaries
    common = sorted(
        set(answered_by_arm["pipelined"])
        & set(answered_by_arm["nopipeline"])
    )
    hashes = {}
    for arm in ("pipelined", "nopipeline"):
        h = hashlib.sha256()
        for key in common:
            h.update(repr(key).encode())
            h.update(answered_by_arm[arm][key] or b"unsolved")
        hashes[arm] = h.hexdigest()
    parity_ok = (
        hashes["pipelined"] == hashes["nopipeline"]
        and all(r["failed"] == 0 for rows_ in window_stats.values() for r in rows_)
    )

    def med(arm, key):
        vals = [r[key] for r in window_stats[arm]]
        return round(statistics.median(vals), 2) if vals else 0.0

    # 25×25 boundary-byte probe (off-smoke): ONE real digest-program
    # boundary at width 4 over instantly-UNSAT pads — measures the
    # actual digest fetch next to what the full-row arm would move
    fetch_25 = None
    if not smoke:
        import jax.numpy as jnp

        from sudoku_solver_distributed_tpu.ops import (
            SEGMENT_DIGEST_COLS,
            init_segment_state,
            inject_lanes_src,
            run_segment,
            segment_digest,
            serving_config,
            spec_for_size,
        )
        from sudoku_solver_distributed_tpu.ops.solver import (
            RUNNING as _RUN,
        )

        spec25 = spec_for_size(25)
        cfg25 = serving_config(25)
        w25 = 4

        def probe(state, boards, src, k):
            state = inject_lanes_src(state, boards, src, spec25)
            entry = state.status == _RUN
            state, st = run_segment(
                state, k, spec25,
                locked_candidates=cfg25["locked_candidates"],
                waves=cfg25["waves"],
                naked_pairs=cfg25["naked_pairs"],
            )
            return segment_digest(state, entry, st)

        jprobe = jax.jit(probe, donate_argnums=(0,))
        st25 = init_segment_state(
            jnp.zeros((w25, 25, 25), jnp.int32), spec25, None
        )
        import warnings

        with warnings.catch_warnings():
            # XLA may decline to alias some 25×25 probe buffers — a
            # layout detail of this one-shot probe, not a finding
            warnings.filterwarnings(
                "ignore", message=".*donated buffers.*"
            )
            digest25, _g = jprobe(
                st25,
                jnp.zeros((w25, 25, 25), jnp.int32),
                # pad re-seeds: die in one sweep
                jnp.full((w25,), -2, jnp.int32),
                jnp.int32(2),
            )
        digest_np = np.array(jax.block_until_ready(digest25))
        full_bytes = w25 * (spec25.cells + 7) * 4
        fetch_25 = {
            "width": w25,
            "digest_bytes_per_boundary": int(digest_np.nbytes),
            "full_row_bytes_per_boundary": int(full_bytes),
            "cut_x": round(full_bytes / digest_np.nbytes, 1),
            "digest_cols": int(SEGMENT_DIGEST_COLS),
        }

    record = {
        "metric": "continuous_pipeline_sustained_pps_9x9",
        "value": med("pipelined", "sustained_pps"),
        "unit": "resolved_boards_per_s",
        # >1.0 = the pipelined boundary resolved more boards per wall
        # second than the PR 12 boundary under the identical schedule
        # (median paired ratio; acceptance >= 1.10)
        "vs_baseline": round(pps_ratio, 4),
        "nopipeline_sustained_pps": med("nopipeline", "sustained_pps"),
        "boundary_host_ms_per_segment": {
            "pipelined": med("pipelined", "boundary_host_ms_per_segment"),
            "nopipeline": med(
                "nopipeline", "boundary_host_ms_per_segment"
            ),
        },
        "fetch_bytes_per_segment": {
            "pipelined": med("pipelined", "fetch_bytes_per_segment"),
            "nopipeline": med("nopipeline", "fetch_bytes_per_segment"),
        },
        "fetch_bytes_25x25_probe": fetch_25,
        "util_pct": {
            "pipelined": med("pipelined", "util_pct"),
            "nopipeline": med("nopipeline", "util_pct"),
        },
        "p99_ms": {
            "pipelined": med("pipelined", "p99_ms"),
            "nopipeline": med("nopipeline", "p99_ms"),
        },
        "p50_ms": {
            "pipelined": med("pipelined", "p50_ms"),
            "nopipeline": med("nopipeline", "p50_ms"),
        },
        "goodput_pps": {
            "pipelined": med("pipelined", "goodput_pps"),
            "nopipeline": med("nopipeline", "goodput_pps"),
        },
        "cost_evidence": cost_evidence,
        "capacity_pps_baseline": round(capacity, 1),
        "open_loop_rate_pps": round(rate, 1),
        "overload_x": over_x,
        "deadline_ms": deadline_ms,
        "window_secs": secs,
        "pairs": pairs,
        "requests_per_window": len(arrivals),
        "platform": platform,
        "pinned_core": pinned,
        # host concurrency matters for THIS mode: the pipelined
        # boundary's overlap machinery (speculative dispatch, injection
        # prestage, dispatch-before-resolve) needs a host that can run
        # driver python and device compute at the same time — on a
        # single-CPU host the arms converge to parity and the win shows
        # in the boundary gauges (boundary_host_ms, fetch bytes), not
        # wall clock
        "host_cpus": os.cpu_count(),
        "pool": {
            "boards": int(len(pool)),
            "easy": int(len(easy)),
            "deep": int(len(hard)),
        },
        "segment_iters": seg_iters,
        # the PR 13 fairness-sweep knob's evidence
        # (BENCH_CONTINUOUS_DEEP_LANE_CAP): which cap this artifact ran
        # with; per-arm eviction counts ride cost_evidence
        "deep_lane_cap": engines["pipelined"].deep_lane_cap,
        "paired_pps_rows": rows,
        "paired_pps_ratios_sorted": ratios,
        "windows": window_stats,
        "parity": {
            "ok": parity_ok,
            "common_answers": len(common),
            "hashes": hashes,
            "reference_hash": ref_hash,
        },
        "smoke": smoke,
    }
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    headline = {
        k: record[k] for k in ("metric", "value", "unit", "vs_baseline")
    }
    print(json.dumps(headline))
    bh = record["boundary_host_ms_per_segment"]
    print(
        f"# continuous pipeline: sustained {record['value']} vs "
        f"{record['nopipeline_sustained_pps']} pps (ratio "
        f"{pps_ratio:.3f}) | boundary host {bh['pipelined']} vs "
        f"{bh['nopipeline']} ms/seg | p99 "
        f"{record['p99_ms']['pipelined']} vs "
        f"{record['p99_ms']['nopipeline']} ms | parity "
        f"{parity_ok} common={len(common)} | rate={rate:.0f}pps "
        f"({over_x}x of {capacity:.0f}) | artifact: {out_path}",
        file=sys.stderr,
    )
    if not parity_ok:
        sys.exit(4)


def main_cache():
    """Canonical-form answer cache A/B (ISSUE 13): cache-on vs cache-off
    under a Zipf-distributed overload mix where every arrival is a
    random SYMMETRY of its puzzle (transpose × band/stack × row/col
    perms × digit relabel — cache/canonical.py), so an exact-match cache
    would hit ~never and the canonical form does the work.

    Both arms replay the IDENTICAL schedule (arrival times, puzzle
    indices, symmetry draws) through the REAL front door
    (net/http_api.solve_route: cache lookup → admission → engine) in
    order-flipped paired windows (run_paired_windows). Per window:
    deadline-conditioned goodput (answered/s — the headline paired
    ratio), shed count, hit count, hit-path p50 and dispatch p50.

    Acceptance evidence in the artifact: median paired goodput ratio
    > 1, hit-path p50 ≥ 100× below the cache-off dispatch p50, hit rate
    under the Zipf mix, and sha256 parity over commonly-answered
    requests (unique-solution puzzles: a cached de-canonicalized answer
    must be bit-identical to a computed one).

    Artifact: benchmarks/cache_pr13.json (BENCH_CACHE_OUT overrides).
    ``--smoke`` (or BENCH_CACHE_SMOKE=1): short windows for CI plumbing.
    """
    smoke = (
        "--smoke" in sys.argv[1:]
        or os.environ.get("BENCH_CACHE_SMOKE") == "1"
    )
    import hashlib
    import statistics
    import threading
    from concurrent.futures import ThreadPoolExecutor

    import jax

    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    if platform:
        jax.config.update("jax_platforms", platform)

    import numpy as np

    from sudoku_solver_distributed_tpu.cache import AnswerCache, CacheGossip
    from sudoku_solver_distributed_tpu.cache.canonical import random_symmetry
    from sudoku_solver_distributed_tpu.engine import SolverEngine
    from sudoku_solver_distributed_tpu.models import generate_batch
    from sudoku_solver_distributed_tpu.net import http_api
    from sudoku_solver_distributed_tpu.net.node import P2PNode
    from sudoku_solver_distributed_tpu.serving import AdmissionController

    repo = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "BENCH_CACHE_OUT",
        os.path.join(repo, "benchmarks", "cache_pr13.json"),
    )
    pairs = int(os.environ.get("BENCH_CACHE_PAIRS", "2" if smoke else "3"))
    secs = float(os.environ.get("BENCH_CACHE_SECS", "1.5" if smoke else "6"))
    over_x = float(os.environ.get("BENCH_CACHE_X", "2"))
    deadline_ms = float(os.environ.get("BENCH_CACHE_DEADLINE_MS", "400"))
    pool_n = int(os.environ.get("BENCH_CACHE_POOL", "24" if smoke else "64"))
    zipf_s = float(os.environ.get("BENCH_CACHE_ZIPF_S", "1.1"))
    workers = int(os.environ.get("BENCH_CACHE_WORKERS", "192"))

    # pin to one core on CPU (the hotloop/overload/continuous
    # discipline): the A/B must not drown in migration noise
    pinned = False
    if hasattr(os, "sched_setaffinity") and platform == "cpu":
        try:
            cores = sorted(os.sched_getaffinity(0))
            os.sched_setaffinity(0, {cores[0]})
            pinned = True
        except OSError:
            pass

    # unique-solution pool: parity across arms NEEDS uniqueness — the
    # same board must have exactly one valid answer whichever path
    # (cache, device, fallback) produced it. HARD class (the headline
    # corpus's 64-hole shape), deliberately: a viral puzzle worth
    # caching is a hard one, and on the CPU fallback an easy 30-hole
    # board's amortized batch-8 solve (~0.2 ms) is CHEAPER than the
    # ~0.5 ms canonicalization — the cache A/B is only meaningful where
    # dispatch dominates the reduction, which is every real deployment
    # shape (TPU dispatch, deep boards, queueing under overload)
    holes = int(os.environ.get("BENCH_CACHE_HOLES", "64"))
    pool = generate_batch(pool_n, holes, seed=20260813, unique=True)

    def make_node(with_cache):
        eng = SolverEngine(buckets=(1, 8), coalesce_max_batch=8)
        eng.warmup()
        node = P2PNode(
            "127.0.0.1", 0, engine=eng,
            admission=AdmissionController(capacity=256),
        )
        if with_cache:
            node.answer_cache = AnswerCache(capacity=4096)
            node.cache_gossip = CacheGossip(node.answer_cache, node)
        return node

    nodes = {"cache": make_node(True), "nocache": make_node(False)}

    # closed-loop capacity of the CACHE-OFF arm sets the open-loop rate
    # (the same calibration shape as --mode continuous)
    def measure_capacity(node, warm_s=1.5, clients=8):
        stop = time.monotonic() + warm_s
        counts = [0] * clients

        def client(i):
            while time.monotonic() < stop:
                body = json.dumps(
                    {"sudoku": pool[(i * 7 + counts[i]) % len(pool)].tolist()}
                ).encode()
                status, _p, _e, _d, _c = http_api.solve_route(node, body)
                assert status == 200
                counts[i] += 1

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / warm_s

    capacity = measure_capacity(nodes["nocache"])
    rate = max(10.0, over_x * capacity)

    # ONE schedule: Poisson arrival times + Zipf puzzle indices + the
    # symmetry draw per arrival, all seeded — every window/arm replays
    # the identical request stream byte for byte
    sched_rng = np.random.default_rng(20260814)
    ranks = np.arange(1, len(pool) + 1, dtype=np.float64)
    probs = ranks ** (-zipf_s)
    probs /= probs.sum()
    arrivals = []  # (t, seq, request-body bytes, puzzle idx)
    t = 0.0
    seq = 0
    while t < secs:
        idx = int(sched_rng.choice(len(pool), p=probs))
        board = random_symmetry(pool[idx], sched_rng)
        arrivals.append(
            (t, seq, json.dumps({"sudoku": board}).encode(), idx)
        )
        t += float(sched_rng.exponential(1.0 / rate))
        seq += 1

    answered_by_arm = {"cache": {}, "nocache": {}}
    window_stats = {"cache": [], "nocache": []}
    window_idx = {"n": 0}

    def drive(arm):
        node = nodes[arm]
        w = window_idx["n"]
        window_idx["n"] += 1
        lock = threading.Lock()
        lats, hit_lats, dispatch_lats = [], [], []
        shed = [0]
        hits = [0]

        def one(item):
            dt, s, body, _idx = item
            target = t0 + dt
            now = time.monotonic()
            if target > now:
                time.sleep(target - now)
            t_sub = time.monotonic()
            status, payload, _err, _deg, cached = http_api.solve_route(
                node, body, deadline_ms=deadline_ms
            )
            lat = time.monotonic() - t_sub
            with lock:
                if status == 429:
                    shed[0] += 1
                    return
                if status != 200:
                    return
                lats.append(lat)
                (hit_lats if cached else dispatch_lats).append(lat)
                if cached:
                    hits[0] += 1
                answered_by_arm[arm][(w // 2, s)] = np.asarray(
                    payload, np.int32
                ).tobytes()

        t0 = time.monotonic()
        with ThreadPoolExecutor(max_workers=workers) as ex:
            list(ex.map(one, arrivals))
        wall = time.monotonic() - t0

        def pct(vals, q):
            if not vals:
                return 0.0
            vals = sorted(vals)
            return round(vals[int(q * (len(vals) - 1))] * 1e3, 3)

        row = {
            "arm": arm,
            "answered": len(lats),
            "shed": shed[0],
            "hits": hits[0],
            "goodput_pps": round(len(lats) / wall, 1),
            "p50_ms": pct(lats, 0.50),
            "p99_ms": pct(lats, 0.99),
            "hit_p50_ms": pct(hit_lats, 0.50),
            "dispatch_p50_ms": pct(dispatch_lats, 0.50),
        }
        window_stats[arm].append(row)
        return max(len(lats) / wall, 1e-9)

    rows, ratios, goodput_ratio = run_paired_windows(
        [
            ("cache", lambda: drive("cache")),
            ("nocache", lambda: drive("nocache")),
        ],
        pairs,
        ratio_of=("cache", "nocache"),
    )

    cache_snap = nodes["cache"].answer_cache.snapshot()
    for node in nodes.values():
        node.engine.close()

    # parity: commonly-answered requests must be byte-identical across
    # arms — a de-canonicalized cached answer IS the computed answer
    common = sorted(
        set(answered_by_arm["cache"]) & set(answered_by_arm["nocache"])
    )
    hashes = {}
    mismatches = 0
    for arm in ("cache", "nocache"):
        h = hashlib.sha256()
        for key in common:
            h.update(repr(key).encode())
            h.update(answered_by_arm[arm][key])
        hashes[arm] = h.hexdigest()
    for key in common:
        if answered_by_arm["cache"][key] != answered_by_arm["nocache"][key]:
            mismatches += 1
    parity_ok = (
        mismatches == 0 and hashes["cache"] == hashes["nocache"]
    )

    def med(arm, key):
        vals = [r[key] for r in window_stats[arm]]
        return round(statistics.median(vals), 3) if vals else 0.0

    total_answered = sum(r["answered"] for r in window_stats["cache"])
    total_hits = sum(r["hits"] for r in window_stats["cache"])
    hit_rate = (
        round(100.0 * total_hits / total_answered, 2)
        if total_answered
        else 0.0
    )
    # hit-path p50 over windows that RECORDED hits only: a zero-hit
    # window's 0.0 placeholder is an absence of data, and folding it
    # into the median would deflate hit_p50 and spuriously inflate the
    # >=100x speedup the CI bar asserts
    hit_windows = [
        r["hit_p50_ms"] for r in window_stats["cache"] if r["hits"] > 0
    ]
    hit_p50 = (
        round(statistics.median(hit_windows), 3) if hit_windows else 0.0
    )
    dispatch_p50 = med("nocache", "p50_ms")
    speedup = (
        round(dispatch_p50 / hit_p50, 1) if hit_p50 > 0 else 0.0
    )

    record = {
        "metric": "answer_cache_goodput_ratio_zipf_overload_9x9",
        "value": round(goodput_ratio, 4),
        "unit": "paired_goodput_ratio_cache_on_vs_off",
        # >1.0 = canonical-form caching bought goodput under the
        # identical Zipf overload schedule
        "vs_baseline": round(goodput_ratio, 4),
        "goodput_pps": {
            "cache": med("cache", "goodput_pps"),
            "nocache": med("nocache", "goodput_pps"),
        },
        "hit_rate_pct": hit_rate,
        "hit_p50_ms": hit_p50,
        "dispatch_p50_ms_nocache": dispatch_p50,
        "hit_vs_dispatch_speedup": speedup,
        "p99_ms": {
            "cache": med("cache", "p99_ms"),
            "nocache": med("nocache", "p99_ms"),
        },
        "shed": {
            "cache": sum(r["shed"] for r in window_stats["cache"]),
            "nocache": sum(r["shed"] for r in window_stats["nocache"]),
        },
        "capacity_pps_nocache": round(capacity, 1),
        "open_loop_rate_pps": round(rate, 1),
        "overload_x": over_x,
        "deadline_ms": deadline_ms,
        "zipf_s": zipf_s,
        "pool_puzzles": len(pool),
        "requests_per_window": len(arrivals),
        "window_secs": secs,
        "pairs": pairs,
        "platform": platform,
        "pinned_core": pinned,
        "cache_counters": cache_snap,
        "paired_goodput_rows": rows,
        "paired_goodput_ratios_sorted": ratios,
        "windows": window_stats,
        "parity": {
            "ok": parity_ok,
            "common_answers": len(common),
            "mismatches": mismatches,
            "hashes": hashes,
        },
        "smoke": smoke,
    }
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    headline = {
        k: record[k] for k in ("metric", "value", "unit", "vs_baseline")
    }
    print(json.dumps(headline))
    print(
        f"# cache: goodput ratio {goodput_ratio:.3f} "
        f"({record['goodput_pps']['cache']} vs "
        f"{record['goodput_pps']['nocache']} pps) | hit rate "
        f"{hit_rate}% | hit p50 {hit_p50} ms vs dispatch p50 "
        f"{dispatch_p50} ms ({speedup}x) | parity {parity_ok} "
        f"common={len(common)} | rate={rate:.0f}pps "
        f"({over_x}x of {capacity:.0f}) | artifact: {out_path}",
        file=sys.stderr,
    )
    if not parity_ok:
        sys.exit(4)


def main_chaos():
    """Kill-N-of-M fleet chaos A/B: the fleet autopilot's proof (ISSUE 14).

    An M-node CLI fleet (anchor + master + workers) serves open-loop
    overload on the task-farm path while the harness injects the three
    classic fleet faults mid-run — a SIGKILL'd worker (crash), a
    SIGSTOP/SIGCONT-cycled worker (the straggling-but-alive node hedging
    exists for), and a worker whose ENGINE is poisoned through the PR 5
    fault injector over ``POST /debug/faults`` (silent wrong answers;
    its supervisor catches them host-side, trips the breaker, and
    gossips DEGRADED/LOST) — then clears them and watches the fleet
    recover WITH NO OPERATOR ACTION. Two arms under the identical
    Poisson schedule and identical fault timeline:

      1. autopilot — the default stack: burn-aware admission tightening,
         telemetry-weighted farming, hedged dispatch, elastic
         membership (a fresh joiner boots during recovery and must
         defer its join until warm);
      2. baseline — ``--no-autopilot`` on every node: the PR 13 stack
         (LOST-skip only, sorted farm order, fixed admission budget).

    Both arms run the master with ``--slo`` on short windows
    (``--slo-windows``) so fast-burn detection AND recovery are
    observable inside the run; a scraper thread records the burn /
    budget-scale / hedge-counter timeline at ~2 Hz. GOODPUT is
    deadline-conditioned (a 200 after the deadline is a wasted farm,
    not a served user) and reported per phase (healthy / fault /
    recovery); the headline is the fault-window goodput ratio
    (acceptance ≥ 1.2×). EVERY 200 body is rule-verified host-side by
    the harness in both arms — the autopilot must never trade
    correctness for tail latency — and hedges must stay under the
    budget (max(1, frac × primaries)).

    Artifact: benchmarks/chaos_pr14.json (BENCH_CHAOS_OUT). ``--smoke``
    shrinks the fleet and the windows for CI (autopilot-smoke asserts:
    artifact parses, ≥1 hedge won, fast burn recovered, zero incorrect
    answers).
    """
    import signal
    import subprocess
    import threading
    import urllib.error
    import urllib.request

    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch

    smoke = "--smoke" in sys.argv[1:]
    n_nodes = int(
        os.environ.get("BENCH_CHAOS_NODES", "4" if smoke else "5")
    )
    assert n_nodes >= 4, "chaos mode needs >= 4 nodes (master + 3 peers)"
    healthy_s = float(
        os.environ.get("BENCH_CHAOS_HEALTHY_S", "5" if smoke else "8")
    )
    fault_s = float(
        os.environ.get("BENCH_CHAOS_FAULT_S", "9" if smoke else "14")
    )
    recovery_s = float(
        os.environ.get("BENCH_CHAOS_RECOVERY_S", "11" if smoke else "14")
    )
    deadline_ms = float(os.environ.get("BENCH_CHAOS_DEADLINE_MS", "2000"))
    xmult = float(os.environ.get("BENCH_CHAOS_X", "1.5"))
    holes = int(os.environ.get("BENCH_CHAOS_HOLES", "6"))
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    repo = os.path.dirname(os.path.abspath(__file__))
    base_http = 21000 + os.getpid() % 500

    boards = [
        b.tolist()
        for b in generate_batch(8, holes, seed=20260804, unique=True)
    ]
    bodies = [json.dumps({"sudoku": b}).encode() for b in boards]

    def board_ok(board, solution):
        """Host-side rule verification of one served answer: clue match
        + every row/col/box a permutation of 1..N."""
        n = len(board)
        box = int(round(n ** 0.5))
        full = set(range(1, n + 1))
        for i in range(n):
            for j in range(n):
                if board[i][j] and solution[i][j] != board[i][j]:
                    return False
        for i in range(n):
            if set(solution[i]) != full:
                return False
            if {solution[k][i] for k in range(n)} != full:
                return False
        for bi in range(0, n, box):
            for bj in range(0, n, box):
                cells = {
                    solution[bi + di][bj + dj]
                    for di in range(box)
                    for dj in range(box)
                }
                if cells != full:
                    return False
        return True

    def scrape(port, path, timeout=5):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout
        ) as r:
            return json.loads(r.read())

    def post_faults(port, cmd):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/faults",
            data=json.dumps(cmd).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def run_arm(arm_name, port_base, autopilot_on):
        http_ports = [port_base + i for i in range(n_nodes + 1)]
        udp_ports = [p - 2000 for p in http_ports]
        master = http_ports[1]
        common = [
            "-h", "0", "--platform", platform, "--no-answer-cache",
            "--buckets", "1,8", "--metrics", "--http-workers", "64",
            "--failure-timeout", "5",
        ]
        if not autopilot_on:
            common = common + ["--no-autopilot"]
        procs = {}

        def boot(i, extra, anchor=True):
            cmd = [
                sys.executable, os.path.join(repo, "node.py"),
                "-p", str(http_ports[i]), "-s", str(udp_ports[i]),
            ] + common + extra
            if anchor and i > 0:
                cmd += ["-a", f"127.0.0.1:{udp_ports[0]}"]
            procs[i] = subprocess.Popen(
                cmd, cwd=repo,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )

        results = []       # (t_arrival, status, latency_ms, correct)
        res_lock = threading.Lock()
        timeline = []      # scraper rows
        stop_scraper = threading.Event()

        def post_solve(k, timeout_s):
            req = urllib.request.Request(
                f"http://127.0.0.1:{master}/solve",
                data=bodies[k % len(bodies)],
                headers={"Content-Type": "application/json"},
            )
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=timeout_s) as r:
                    payload = json.loads(r.read())
                    status = r.status
            except urllib.error.HTTPError as e:
                e.read()
                return e.code, (time.perf_counter() - t0) * 1e3, True
            except Exception:
                return 0, (time.perf_counter() - t0) * 1e3, True
            ok = isinstance(payload, list) and board_ok(
                boards[k % len(bodies)], payload
            )
            return status, (time.perf_counter() - t0) * 1e3, ok

        try:
            # anchor first, then the rest (the autopilot arm's joiners
            # defer their dial until tier-0 warm — elastic membership)
            boot(0, [])
            time.sleep(0.3)
            boot(
                1,
                [
                    "--admission-capacity", "64",
                    "--default-deadline-ms", str(deadline_ms),
                    # the objective sits at deadline/4: healthy-phase
                    # p99 clears it, the fault window breaches it even
                    # on the hedging arm (a hedged rescue pays ~the
                    # hedge threshold + a second RTT), so BOTH arms'
                    # burn timelines are observable — and recovery on
                    # the autopilot arm is the artifact's proof
                    "--slo",
                    f"latency_p99_ms={deadline_ms / 4:g}@99",
                    "--slo-windows", "4,12",
                    "--serving-stats",
                ],
            )
            for i in range(2, n_nodes):
                boot(
                    i,
                    [
                        "--supervise-engine", "--chaos-injector",
                        "--breaker-threshold", "2",
                        "--probe-interval-s", "1",
                    ],
                )
            deadline = time.time() + 240
            for i in range(n_nodes):
                while True:
                    if procs[i].poll() is not None:
                        raise RuntimeError(
                            f"node {i} exited rc={procs[i].returncode}"
                        )
                    try:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{http_ports[i]}/readyz",
                            timeout=2,
                        ) as r:
                            if r.status == 200:
                                break
                    except urllib.error.HTTPError:
                        pass
                    except Exception:
                        pass
                    if time.time() > deadline:
                        raise RuntimeError(f"node {i} never became ready")
                    time.sleep(0.5)
            # convergence: the master sees all peers
            while True:
                try:
                    view = scrape(master, "/network")
                    ids = set(view)
                    for vs in view.values():
                        ids.update(vs)
                    if len(ids) >= n_nodes:
                        break
                except Exception:
                    pass
                if time.time() > deadline:
                    raise RuntimeError("fleet did not converge")
                time.sleep(0.5)

            # warm + calibrate the farm path (sequential closed loop)
            lat = []
            fast = 0
            while fast < 3 and time.time() < deadline:
                status, ms, ok = post_solve(len(lat), 60)
                assert status == 200 and ok, (
                    f"warm solve failed: {status}"
                )
                lat.append(ms)
                fast = fast + 1 if ms < 800 else 0
            cal = lat[-6:]
            capacity = 1e3 / max(1.0, float(np.mean(cal)))
            rate = max(2.0, capacity * xmult)

            def scraper():
                while not stop_scraper.is_set():
                    row = {"t": time.perf_counter()}
                    try:
                        m = scrape(master, "/metrics", timeout=2)
                        slo_b = m.get("slo", {})
                        row["fast_burn"] = slo_b.get("fast_burn_active")
                        row["fast_burn_events"] = slo_b.get(
                            "fast_burn_events"
                        )
                        adm = m.get("admission", {})
                        row["budget_scale"] = adm.get("budget_scale")
                        row["pending"] = adm.get("pending")
                        ap = m.get("autopilot")
                        if ap:
                            row["hedges"] = ap["hedge"]["fired"]
                            row["hedge_wins"] = ap["hedge"]["won"]
                            row["tightens"] = ap["admission"]["tightens"]
                        c = scrape(master, "/metrics/cluster", timeout=2)
                        row["ready_nodes"] = c["fleet"].get("ready_nodes")
                        row["fleet_nodes"] = c["fleet"].get("nodes")
                    except Exception:
                        row["scrape_error"] = True
                    timeline.append(row)
                    stop_scraper.wait(0.5)

            scr = threading.Thread(target=scraper, daemon=True)
            scr.start()

            # one seeded schedule for the whole drive window — identical
            # across arms by construction
            drive_s = healthy_s + fault_s + recovery_s
            n_arr = max(8, int(rate * drive_s))
            arrivals = (
                np.random.default_rng(20260804)
                .exponential(1.0 / rate, size=n_arr)
                .cumsum()
            )
            arrivals = arrivals[arrivals < drive_s]

            t0 = time.perf_counter()
            t_fault = t0 + healthy_s
            t_recover = t_fault + fault_s

            def fire(k, at):
                delay = t0 + at - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                t_arr = time.perf_counter() - t0
                status, ms, ok = post_solve(k, deadline_ms / 1e3 * 3)
                with res_lock:
                    results.append((t_arr, status, ms, ok))

            threads = [
                threading.Thread(target=fire, args=(k, at), daemon=True)
                for k, at in enumerate(arrivals)
            ]
            for t in threads:
                t.start()

            # fault timeline (identical across arms): kill one worker,
            # SIGSTOP-cycle another (the straggler), poison a third's
            # engine through the PR 5 injector — all mid-overload
            events = []

            def note(ev):
                events.append(
                    {"t": round(time.perf_counter() - t0, 3), "event": ev}
                )

            kill_i = n_nodes - 1
            stop_i = n_nodes - 2
            poison_i = 2 if n_nodes > 4 else None
            while time.perf_counter() < t_fault:
                time.sleep(0.05)
            procs[kill_i].kill()
            note(f"SIGKILL node{kill_i}")
            if poison_i is not None:
                try:
                    post_faults(
                        http_ports[poison_i], {"poison_bucket": 1}
                    )
                    note(f"poison node{poison_i} bucket 1")
                except Exception as e:
                    note(f"poison node{poison_i} failed: {e}")
            # stop/cont cycles until the recovery point
            stopped = False
            while time.perf_counter() < t_recover:
                if not stopped:
                    procs[stop_i].send_signal(signal.SIGSTOP)
                    note(f"SIGSTOP node{stop_i}")
                    stopped = True
                    t_next = time.perf_counter() + 3.5
                else:
                    procs[stop_i].send_signal(signal.SIGCONT)
                    note(f"SIGCONT node{stop_i}")
                    stopped = False
                    t_next = time.perf_counter() + 2.0
                while (
                    time.perf_counter() < min(t_next, t_recover)
                ):
                    time.sleep(0.05)
            # recovery: clear every fault; NO operator action touches
            # admission/routing — the autopilot must do that part
            if stopped:
                procs[stop_i].send_signal(signal.SIGCONT)
                note(f"SIGCONT node{stop_i}")
            if poison_i is not None:
                try:
                    post_faults(http_ports[poison_i], {"clear": True})
                    note(f"clear node{poison_i} faults")
                except Exception as e:
                    note(f"clear node{poison_i} failed: {e}")
            joiner = None
            if not smoke:
                # elastic membership under traffic: a fresh worker boots
                # during recovery; on the autopilot arm it defers its
                # join until tier-0 warm, then prewarms
                boot(n_nodes, [
                    "--supervise-engine",
                ])
                joiner = {"booted_at": round(
                    time.perf_counter() - t0, 3
                )}
                note(f"boot joiner node{n_nodes}")

            for t in threads:
                t.join(timeout=drive_s + 30)
            if joiner is not None:
                jdeadline = time.time() + 60
                while time.time() < jdeadline:
                    try:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{http_ports[n_nodes]}"
                            f"/readyz",
                            timeout=2,
                        ) as r:
                            if r.status == 200:
                                joiner["ready_at"] = round(
                                    time.perf_counter() - t0, 3
                                )
                                break
                    except Exception:
                        pass
                    time.sleep(0.5)
                try:
                    view = scrape(master, "/network")
                    ids = set(view)
                    for vs in view.values():
                        ids.update(vs)
                    joiner["in_master_view"] = (
                        f"127.0.0.1:{udp_ports[n_nodes]}" in ids
                    )
                except Exception:
                    pass
            # let the burn windows drain past the fault, then read the
            # final control-plane state
            settle = time.perf_counter() + (4.0 if smoke else 6.0)
            while time.perf_counter() < settle:
                time.sleep(0.25)
            final = {}
            try:
                m = scrape(master, "/metrics")
                final["slo"] = {
                    k: m.get("slo", {}).get(k)
                    for k in (
                        "fast_burn_active", "fast_burn_events",
                    )
                }
                final["admission"] = {
                    k: m.get("admission", {}).get(k)
                    for k in (
                        "budget_scale", "shed_deadline",
                        "shed_capacity", "completed", "expired",
                    )
                }
                if m.get("autopilot"):
                    final["autopilot"] = m["autopilot"]
                cost = m.get("engine", {}).get("cost", {})
                if cost.get("farm"):
                    final["farm_cost"] = cost["farm"]
            except Exception as e:
                final["scrape_error"] = repr(e)
            stop_scraper.set()
            scr.join(timeout=5)

            # phase split by ARRIVAL time; goodput = 200s answered
            # within the deadline, over the phase wall
            def phase(rows, a, b):
                sel = [r for r in rows if a <= r[0] < b]
                ok200 = [
                    r for r in sel if r[1] == 200 and r[2] <= deadline_ms
                ]
                late200 = [
                    r for r in sel if r[1] == 200 and r[2] > deadline_ms
                ]
                return {
                    "offered": len(sel),
                    "goodput_pps": round(len(ok200) / max(b - a, 1e-6), 2),
                    "late_200s": len(late200),
                    "shed": sum(1 for r in sel if r[1] == 429),
                    "errors": sum(
                        1 for r in sel if r[1] not in (200, 429)
                    ),
                    "p99_ms": round(
                        float(
                            np.percentile([r[2] for r in ok200], 99)
                        ),
                        1,
                    )
                    if ok200
                    else None,
                }

            with res_lock:
                rows = list(results)
            incorrect = sum(
                1 for r in rows if r[1] == 200 and not r[3]
            )
            arm_out = {
                "autopilot": autopilot_on,
                "capacity_pps_est": round(capacity, 2),
                "offered_rps": round(rate, 2),
                "phases": {
                    "healthy": phase(rows, 0.0, healthy_s),
                    "fault": phase(
                        rows, healthy_s, healthy_s + fault_s
                    ),
                    "recovery": phase(
                        rows, healthy_s + fault_s, drive_s
                    ),
                },
                "answered_200": sum(1 for r in rows if r[1] == 200),
                "incorrect_200s": incorrect,
                "events": events,
                "final": final,
                "timeline": timeline[-80:],
            }
            if joiner is not None:
                arm_out["joiner"] = joiner
            assert incorrect == 0, (
                f"{arm_name}: {incorrect} rule-invalid answers served"
            )
            return arm_out
        finally:
            stop_scraper.set()
            for p in procs.values():
                try:
                    p.send_signal(signal.SIGCONT)
                except Exception:
                    pass
                p.terminate()
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    on = run_arm("autopilot", base_http, True)
    off = run_arm("baseline", base_http + 40, False)

    ratio = (
        round(
            on["phases"]["fault"]["goodput_pps"]
            / off["phases"]["fault"]["goodput_pps"],
            3,
        )
        if off["phases"]["fault"]["goodput_pps"]
        else None
    )
    ap_final = on["final"].get("autopilot", {})
    hedge = ap_final.get("hedge", {})
    budget_ok = hedge.get("fired", 0) <= max(
        1, hedge.get("budget_frac", 0.25) * hedge.get(
            "primary_dispatches", 0
        )
    )
    burn_recovered = (
        on["final"].get("slo", {}).get("fast_burn_active") is False
        and (on["final"].get("slo", {}).get("fast_burn_events") or 0) >= 1
    )
    record = {
        "metric": (
            f"chaos_fault_window_goodput_ratio_{n_nodes}node"
        ),
        "value": ratio,
        "unit": "x",
        "vs_baseline": ratio,
        "nodes": n_nodes,
        "deadline_ms": deadline_ms,
        "holes": holes,
        "windows_s": {
            "healthy": healthy_s, "fault": fault_s,
            "recovery": recovery_s,
        },
        "hedge": {
            "fired": hedge.get("fired"),
            "won": hedge.get("won"),
            "denied_budget": hedge.get("denied_budget"),
            "late_dups": hedge.get("late_dups"),
            "primary_dispatches": hedge.get("primary_dispatches"),
            "budget_ok": budget_ok,
        },
        "slo_recovered_no_operator_action": burn_recovered,
        "admission_tightens": ap_final.get("admission", {}).get(
            "tightens"
        ),
        "incorrect_200s": {
            "autopilot": on["incorrect_200s"],
            "baseline": off["incorrect_200s"],
        },
        "arms": {"autopilot": on, "baseline": off},
    }
    out_path = os.environ.get("BENCH_CHAOS_OUT")
    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1)
    print(json.dumps({
        k: v for k, v in record.items() if k != "arms"
    }))
    print(
        f"# chaos: nodes={n_nodes} offered={on['offered_rps']}rps "
        f"fault-window goodput on={on['phases']['fault']['goodput_pps']} "
        f"off={off['phases']['fault']['goodput_pps']} ratio={ratio} | "
        f"hedges fired={hedge.get('fired')} won={hedge.get('won')} "
        f"late_dups={hedge.get('late_dups')} budget_ok={budget_ok} | "
        f"tightens={record['admission_tightens']} "
        f"burn_recovered={burn_recovered} | incorrect on="
        f"{on['incorrect_200s']} off={off['incorrect_200s']}",
        file=sys.stderr,
    )


def main_tpu_window():
    """First-class claim-window harness (ISSUE 7): the fold of the ad-hoc
    ``benchmarks/tpu_session_retry*.sh`` scanners into bench.py.

    Phases, each bounded and logged into one machine-readable report that
    is written on EVERY exit path (the round-5 lesson: a 31-minute compile
    or a closed relay port must convert into a diagnosable artifact, not a
    lost window):

      1. SCAN — on the axon platform, probe the relay's terminal ports
         (8082 claim/init, 8093 remote-compile) every
         BENCH_WINDOW_SCAN_INTERVAL_S (default 20 s) up to
         BENCH_WINDOW_SCAN_BUDGET_S (default 900 s), recording every
         open/close transition (the availability timeline is itself a
         round artifact). Window never opens → status ``claim-failed``.
         Non-axon platforms (the CI CPU-fallback run) skip the scan.
      2. BAKE + LADDER — one fresh child per BENCH_WINDOW_SIZES entry
         (default "9") runs the throughput mode against the shared
         persistent compile plane (COMPILE_CACHE_DIR), with the child's
         compile watchdog armed at BENCH_WINDOW_BAKE_BUDGET_S (default
         600 s): a compile that blows the budget kills only that child
         (rc=3, ``compile blocked`` on stderr) → status
         ``compile-budget-exceeded`` with the diagnostic captured; a
         compile that lands is cached, so the NEXT window skips the bake.
         Each child's one-line JSON lands in the report's ladder.

    Status: ``claimed-and-ran`` (≥1 ladder record), ``claim-failed``,
    or ``compile-budget-exceeded``. Exit code 0 only for claimed-and-ran;
    3 otherwise — but the report file and the stdout JSON line exist in
    every case. Report: BENCH_WINDOW_OUT (default
    benchmarks/window_report_pr7.json).

    Test hook: BENCH_WINDOW_FAKE_CLOSED=1 forces the scan to see a closed
    window (drives the claim-failed path without an axon relay).
    """
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "BENCH_WINDOW_OUT",
        os.path.join(repo, "benchmarks", "window_report_pr7.json"),
    )
    scan_budget = float(os.environ.get("BENCH_WINDOW_SCAN_BUDGET_S", "900"))
    scan_interval = float(
        os.environ.get("BENCH_WINDOW_SCAN_INTERVAL_S", "20")
    )
    bake_budget = float(os.environ.get("BENCH_WINDOW_BAKE_BUDGET_S", "600"))
    sizes = [
        int(s)
        for s in os.environ.get("BENCH_WINDOW_SIZES", "9").split(",")
        if s.strip()
    ]
    platform = os.environ.get("BENCH_PLATFORM")
    on_axon = (
        os.environ.get("JAX_PLATFORMS", "") == "axon" and not platform
    )
    fake_closed = os.environ.get("BENCH_WINDOW_FAKE_CLOSED") == "1"

    t_start = time.time()
    report = {
        "mode": "tpu-window",
        "status": "claim-failed",
        "platform": platform or os.environ.get("JAX_PLATFORMS", "default"),
        "started_unix": round(t_start, 1),
        "scan": {
            "performed": bool(on_axon or fake_closed),
            "budget_s": scan_budget,
            "interval_s": scan_interval,
            "probes": 0,
            "transitions": [],
            "opened": False,
        },
        "bake": {
            "budget_s": bake_budget,
            "compile_cache_dir": os.environ.get(
                "JAX_COMPILATION_CACHE_DIR", COMPILE_CACHE_DIR
            ),
        },
        "ladder": [],
        "reason": None,
    }

    def finish(status, reason=None, rc=None):
        report["status"] = status
        report["reason"] = reason
        report["finished_unix"] = round(time.time(), 1)
        report["elapsed_s"] = round(time.time() - t_start, 1)
        # a bare-filename BENCH_WINDOW_OUT has no directory component;
        # makedirs("") would raise and eat the report
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
        print(
            json.dumps(
                {
                    "metric": "tpu_window",
                    "value": 1.0 if status == "claimed-and-ran" else 0.0,
                    "unit": "window",
                    "vs_baseline": None,
                    "status": status,
                    "report": out_path,
                }
            )
        )
        print(f"# tpu-window: {status} — report {out_path}", file=sys.stderr)
        sys.exit(0 if status == "claimed-and-ran" else (rc or 3))

    # --- phase 1: scan ----------------------------------------------------
    if on_axon or fake_closed:
        state = None
        deadline = t_start + scan_budget
        while True:
            open_now = (not fake_closed) and _claim_window_open()
            report["scan"]["probes"] += 1
            new_state = "open" if open_now else "closed"
            if new_state != state:
                report["scan"]["transitions"].append(
                    {"t": round(time.time() - t_start, 1), "state": new_state}
                )
                state = new_state
            if open_now:
                report["scan"]["opened"] = True
                break
            if time.time() + scan_interval > deadline:
                finish(
                    "claim-failed",
                    f"claim window did not open within "
                    f"{scan_budget:.0f}s ({report['scan']['probes']} probes; "
                    f"relay ports 8082/8093 refused connections)",
                )
            time.sleep(scan_interval)

    # --- phase 2: bake + ladder (one fresh child per size) ----------------
    bake_t0 = time.time()
    for size in sizes:
        env = dict(
            os.environ,
            BENCH_CHILD="1",
            BENCH_MODE="throughput",
            BENCH_SIZE=str(size),
            BENCH_COMPILE_TIMEOUT_S=str(bake_budget),
        )
        env.pop("BENCH_HOTLOOP_SMOKE", None)
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env,
                capture_output=True,
                text=True,
                # hard stop: the scan/bake budgets plus slack — a wedged
                # child must not eat the driver's outer window (the child's
                # own watchdogs normally fire long before this)
                timeout=bake_budget + 1200,
            )
            rc, stdout, stderr = proc.returncode, proc.stdout, proc.stderr
        except subprocess.TimeoutExpired as e:
            rc = -9
            stdout = (e.stdout or b"").decode() if isinstance(
                e.stdout, bytes
            ) else (e.stdout or "")
            stderr = (e.stderr or b"").decode() if isinstance(
                e.stderr, bytes
            ) else (e.stderr or "")
        if stderr:
            print(stderr, end="", file=sys.stderr, flush=True)
        json_lines = [
            ln for ln in (stdout or "").splitlines() if ln.startswith("{")
        ]
        entry = {
            "size": size,
            "rc": _exit_code(rc),
            "elapsed_s": round(time.time() - t0, 1),
            "record": json.loads(json_lines[0]) if json_lines else None,
        }
        report["ladder"].append(entry)
        if rc != 0:
            tail = (stderr or "")[-1500:]
            entry["stderr_tail"] = tail
            if "compile blocked" in tail or "blocked past" in tail:
                report["bake"]["elapsed_s"] = round(time.time() - bake_t0, 1)
                finish(
                    "compile-budget-exceeded",
                    f"size {size}: first transfer/compile exceeded the "
                    f"{bake_budget:.0f}s bake budget (wedged relay or cold "
                    f"cache; the persistent plane keeps any partial bake)",
                )
            finish(
                "claim-failed",
                f"size {size}: bench child failed rc={_exit_code(rc)} "
                f"before landing a record",
            )
    report["bake"]["elapsed_s"] = round(time.time() - bake_t0, 1)
    finish("claimed-and-ran")


def main_coldstart_child():
    """One cold-start probe in a FRESH process (jit caches are per-process;
    only a child can measure a cold start). Builds a SolverEngine with the
    env-selected compile plane, runs the tiered warmup in the background,
    and times: engine-construction→tier-0-warm, →first correct /solve
    answer, →fully warm. Prints ONE JSON line; driven by main_coldstart().

    Env: COLDSTART_BUCKETS (ladder), COLDSTART_CACHE_DIR (compile plane
    root, "" = none — a true cold start), COLDSTART_AOT (use the explicit
    artifact store on top of the XLA cache)."""
    t_proc = time.perf_counter()
    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("BENCH_PLATFORM") or "cpu"
    )
    cache_dir = os.environ.get("COLDSTART_CACHE_DIR") or None
    aot = os.environ.get("COLDSTART_AOT", "0") == "1"
    buckets = tuple(
        int(b)
        for b in os.environ.get("COLDSTART_BUCKETS", "1,8,64").split(",")
    )
    from sudoku_solver_distributed_tpu.engine import SolverEngine
    from sudoku_solver_distributed_tpu.models import oracle_is_valid_solution

    t_import = time.perf_counter() - t_proc
    # the README 8-clue board — the canonical hard serving request
    puzzle = [
        [0, 0, 0, 1, 0, 0, 0, 0, 0],
        [0, 0, 0, 3, 2, 0, 0, 0, 0],
        [0, 0, 0, 0, 0, 9, 0, 0, 0],
        [0, 0, 0, 0, 0, 0, 0, 7, 0],
        [0, 0, 0, 0, 0, 0, 0, 0, 0],
        [0, 0, 0, 9, 0, 0, 0, 0, 0],
        [0, 0, 0, 0, 0, 0, 9, 0, 0],
        [0, 0, 0, 0, 0, 0, 0, 0, 3],
        [0, 0, 0, 0, 0, 0, 0, 0, 0],
    ]
    t0 = time.perf_counter()
    eng = SolverEngine(
        buckets=buckets,
        compile_cache_dir=cache_dir,
        aot_artifacts=aot,
        coalesce=False,
    )
    eng.warmup(background=True)  # returns at tier-0 warm; ladder widens
    t_tier0 = time.perf_counter() - t0
    sol, _info = eng.solve_one(puzzle)
    t_first = time.perf_counter() - t0
    before_full = not eng.fully_warmed
    ok = (
        sol is not None
        and oracle_is_valid_solution(sol)
        and all(
            sol[r][c] == puzzle[r][c]
            for r in range(9)
            for c in range(9)
            if puzzle[r][c]
        )
    )
    deadline = time.time() + 600
    while not eng.fully_warmed and time.time() < deadline:
        time.sleep(0.05)
    t_full = time.perf_counter() - t0
    print(
        json.dumps(
            {
                # timing basis: engine-construction start (interpreter +
                # jax import cost is identical across variants and
                # reported separately as import_s)
                "t_tier0_warm_s": round(t_tier0, 3),
                "t_first_solve_s": round(t_first, 3),
                "t_fully_warm_s": round(t_full, 3),
                "import_s": round(t_import, 3),
                "first_solve_ok": ok,
                "first_solve_before_fully_warm": before_full,
                "fully_warmed": eng.fully_warmed,
                "program_count": eng.program_count(),
                "warm_info": eng.warm_info(),
            }
        ),
        flush=True,
    )
    sys.exit(0 if ok and eng.fully_warmed else 4)


def main_coldstart():
    """A/B the cold-start compiler plane (ISSUE 4) on CPU: three fresh
    child processes measure time-to-first-solve, time-to-tier-0-warm, and
    time-to-fully-warm under {cold, persistent-XLA-cache, AOT-artifact}
    — plus one ``populate`` bake run that pays the compiles into a shared
    plane dir first. Artifact: benchmarks/coldstart_pr4.json (override
    BENCH_COLDSTART_OUT); ladder via BENCH_COLDSTART_BUCKETS (CI smoke
    uses a tiny one). Headline JSON line: warm-vs-cold first-solve
    speedup, vs_baseline normalized to the ≥3× acceptance bar."""
    import shutil
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "BENCH_COLDSTART_OUT",
        os.path.join(repo, "benchmarks", "coldstart_pr4.json"),
    )
    buckets = os.environ.get("BENCH_COLDSTART_BUCKETS", "1,8,64")
    timeout_s = float(os.environ.get("BENCH_COLDSTART_TIMEOUT_S", "900"))
    workdir = tempfile.mkdtemp(prefix="coldstart_bench_")
    plane = os.path.join(workdir, "plane")

    def run_child(label, cache_dir, aot):
        env = dict(os.environ)
        # children own their persistence: a developer-exported cache dir
        # must not quietly warm the "cold" run
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        env.update(
            JAX_PLATFORMS="cpu",
            COLDSTART_BUCKETS=buckets,
            COLDSTART_CACHE_DIR=cache_dir or "",
            COLDSTART_AOT="1" if aot else "0",
        )
        t0 = time.perf_counter()
        proc = subprocess.run(
            [
                sys.executable,
                os.path.abspath(__file__),
                "--mode",
                "coldstart-child",
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        wall = time.perf_counter() - t0
        if proc.stderr:
            print(proc.stderr, end="", file=sys.stderr, flush=True)
        line = next(
            (
                ln
                for ln in proc.stdout.splitlines()
                if ln.startswith("{")
            ),
            None,
        )
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"coldstart child {label!r} failed rc={proc.returncode}: "
                f"{proc.stdout[-500:]}"
            )
        rec = json.loads(line)
        rec["wall_s"] = round(wall, 3)
        print(
            f"# coldstart {label}: first_solve={rec['t_first_solve_s']}s "
            f"tier0={rec['t_tier0_warm_s']}s "
            f"fully_warm={rec['t_fully_warm_s']}s "
            f"sources={[v.get('source') for v in rec['warm_info']['buckets'].values()]}",
            file=sys.stderr,
            flush=True,
        )
        return rec

    try:
        runs = {
            # true cold: no persistent plane at all
            "cold": run_child("cold", None, False),
            # bake: pays the compiles once into the shared plane (XLA
            # disk cache + verified AOT artifacts) — the pre-TPU-window
            # step docs/OPERATIONS.md describes
            "populate": run_child("populate", plane, True),
            # implicit layer only: trace again, compile from disk cache
            "persistent_cache": run_child("persistent_cache", plane, False),
            # explicit artifacts: skip the trace too
            "aot": run_child("aot", plane, True),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    cold_first = runs["cold"]["t_first_solve_s"]
    cold_full = runs["cold"]["t_fully_warm_s"]
    speed_first = {
        k: round(cold_first / max(runs[k]["t_first_solve_s"], 1e-9), 2)
        for k in ("persistent_cache", "aot")
    }
    speed_full = {
        k: round(cold_full / max(runs[k]["t_fully_warm_s"], 1e-9), 2)
        for k in ("persistent_cache", "aot")
    }
    artifact = {
        "mode": "coldstart",
        "platform": "cpu",
        "buckets": [int(b) for b in buckets.split(",")],
        "timing_basis": (
            "seconds from SolverEngine construction in a fresh process "
            "(per-variant identical interpreter+jax import cost reported "
            "as import_s); tiered warmup runs in the background — "
            "t_first_solve_s is a correct, clue-consistent README-board "
            "/solve answer, t_tier0_warm_s when serving flipped warm, "
            "t_fully_warm_s when the whole ladder finished"
        ),
        "runs": runs,
        "speedup_first_solve_vs_cold": speed_first,
        "speedup_fully_warm_vs_cold": speed_full,
        "first_solve_correct_before_fully_warm": bool(
            runs["cold"]["first_solve_ok"]
            and runs["cold"]["first_solve_before_fully_warm"]
        ),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"# coldstart artifact: {out_path}", file=sys.stderr, flush=True)
    best = max(speed_first.values())
    print(
        json.dumps(
            {
                "metric": "coldstart_first_solve_speedup",
                "value": best,
                "unit": "x_vs_cold",
                # acceptance bar: warm-cache first solve >= 3x faster
                # than cold (>=1.0 meets it)
                "vs_baseline": round(best / 3.0, 3),
            }
        )
    )


def main_mesh_scaling_child():
    """One mesh-serving probe in a FRESH fake-device process (driven by
    main_mesh_scaling; the parent set XLA_FLAGS=--xla_force_host_platform_
    device_count=N before this interpreter started — a device count is
    process-birth state). Builds a mesh="auto" engine over the compile
    plane, warms it, solves the seeded corpus through the BATCH path and a
    coalesced closed-loop storm through the SERVING path, and prints ONE
    JSON line: solution hash (topology-parity evidence), batch-split
    counters (output-sharding metadata), coalescer fill, idle-lane loop
    counters from the sharded solver, and the warm sources (AOT evidence).

    Env: MESH_CHILD_BOARDS, MESH_CHILD_BUCKETS, MESH_CHILD_CACHE_DIR
    ("" = no persistent plane), MESH_CHILD_CLIENTS, MESH_CHILD_REQUESTS.
    """
    import hashlib
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from sudoku_solver_distributed_tpu.engine import SolverEngine
    from sudoku_solver_distributed_tpu.models import (
        generate_batch,
        oracle_is_valid_solution,
    )

    n_boards = int(os.environ.get("MESH_CHILD_BOARDS", "256"))
    buckets = tuple(
        int(b)
        for b in os.environ.get("MESH_CHILD_BUCKETS", "8,64").split(",")
    )
    cache_dir = os.environ.get("MESH_CHILD_CACHE_DIR") or None
    clients = int(os.environ.get("MESH_CHILD_CLIENTS", "8"))
    requests = int(os.environ.get("MESH_CHILD_REQUESTS", "4"))
    n_dev = len(jax.devices())

    boards = generate_batch(n_boards, 55, seed=20260803)
    t0 = time.perf_counter()
    eng = SolverEngine(
        mesh="auto",
        buckets=buckets,
        compile_cache_dir=cache_dir,
        coalesce=True,
        coalesce_max_batch=buckets[-1],
    )
    eng.warmup()
    t_warm = time.perf_counter() - t0

    # batch path: the whole corpus through solve_batch_np (tiles over the
    # largest bucket; partial tail = the non-divisible coalesced case)
    t0 = time.perf_counter()
    sols, mask, info = eng.solve_batch_np(boards)
    t_batch = time.perf_counter() - t0
    if not bool(mask.all()):
        print(json.dumps({"error": "batch left boards unsolved"}))
        sys.exit(4)
    sol_hash = hashlib.sha256(
        np.ascontiguousarray(sols, np.int32).tobytes()
    ).hexdigest()

    # serving path: a coalesced closed-loop storm so the mesh dispatch
    # runs under the REAL micro-batching machinery
    errors = []

    def client(k):
        for r in range(requests):
            b = boards[(k * requests + r) % n_boards]
            sol, _ = eng.solve_one(b.tolist())
            if sol is None or not oracle_is_valid_solution(sol):
                errors.append((k, r))

    threads = [
        threading.Thread(target=client, args=(k,)) for k in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    t_serve = time.perf_counter() - t0
    if errors:
        print(json.dumps({"error": f"bad coalesced answers: {errors[:4]}"}))
        sys.exit(4)

    # idle-lane loop counters through the sharded library solver — the
    # machine-independent evidence per-shard compaction still engages
    # (the PR 7 counters, reduced with psum over the mesh)
    lane = {}
    if n_dev > 1:
        from sudoku_solver_distributed_tpu.parallel import (
            default_mesh,
            make_sharded_solver,
        )

        solve = make_sharded_solver(default_mesh())
        _g, _s, stats = solve(boards[: max(n_dev * 8, 16)])
        lane = {
            "lane_steps": int(stats["lane_steps"]),
            "idle_lane_steps": int(stats["idle_lane_steps"]),
        }

    wi = eng.warm_info()
    out = {
        "devices": n_dev,
        "buckets": list(eng.buckets),
        "buckets_requested": list(eng.requested_buckets),
        "boards": n_boards,
        "t_warm_s": round(t_warm, 3),
        "t_batch_s": round(t_batch, 3),
        "batch_pps": round(n_boards / max(t_batch, 1e-9), 1),
        "t_serve_s": round(t_serve, 3),
        "serve_requests": clients * requests,
        "solution_hash": sol_hash,
        "info": info,
        "mesh": eng.mesh_info(),
        "coalescer": eng.coalescer.stats() if eng.coalesce else None,
        "warm_sources": {
            k: v.get("source") for k, v in wi["buckets"].items()
        },
        "aot": wi.get("aot"),
        "lane_counters": lane,
    }
    eng.close()
    print(json.dumps(out), flush=True)
    sys.exit(0)


def main_mesh_scaling():
    """The mesh-parallel serving plane's acceptance artifact (ISSUE 8):
    fresh fake-device children per device count prove (a) coalesced and
    batch answers are byte-identical across topologies, (b) dispatched
    batches provably split N ways (output-sharding counter evidence),
    and (c) a SECOND fresh process per count cold-starts the sharded
    bucket programs from the AOT store (warm sources aot:*). Artifact:
    benchmarks/mesh_pr8.json. Wall-clock is recorded per child but is NOT
    the headline — fake devices share host cores; the multi-chip
    wall-clock headline belongs to --mode tpu-window on real chips.

    ``--smoke`` (or BENCH_MESH_SMOKE=1): tiny corpus/ladder for CI.
    """
    import shutil
    import subprocess
    import tempfile

    from sudoku_solver_distributed_tpu.parallel import sim

    smoke = "--smoke" in sys.argv or os.environ.get("BENCH_MESH_SMOKE") == "1"
    repo = os.path.dirname(os.path.abspath(__file__))
    out_path = os.environ.get(
        "BENCH_MESH_OUT", os.path.join(repo, "benchmarks", "mesh_pr8.json")
    )
    counts = [
        int(c)
        for c in os.environ.get("BENCH_MESH_DEVICES", "1,4").split(",")
    ]
    workdir = tempfile.mkdtemp(prefix="mesh_bench_")
    child_env = {
        "MESH_CHILD_BOARDS": "64" if smoke else "256",
        "MESH_CHILD_BUCKETS": "8,32" if smoke else "8,64",
        "MESH_CHILD_CLIENTS": "6" if smoke else "12",
        "MESH_CHILD_REQUESTS": "3" if smoke else "6",
    }
    timeout_s = float(os.environ.get("BENCH_MESH_TIMEOUT_S", "900"))

    def run_child(n, phase, plane):
        env = sim.fake_device_env(n, compile_cache=os.path.join(plane, "xla"))
        env.update(child_env, MESH_CHILD_CACHE_DIR=plane)
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mode",
             "mesh-scaling-child"],
            env=env, cwd=repo, capture_output=True, text=True,
            timeout=timeout_s,
        )
        wall = time.perf_counter() - t0
        line = next(
            (ln for ln in proc.stdout.splitlines() if ln.startswith("{")),
            None,
        )
        if proc.returncode != 0 or line is None:
            raise RuntimeError(
                f"mesh child n={n} phase={phase} failed "
                f"rc={proc.returncode}:\n{proc.stdout[-1500:]}"
                f"\n{proc.stderr[-1500:]}"
            )
        rec = json.loads(line)
        rec["wall_s"] = round(wall, 3)
        print(
            f"# mesh n={n} {phase}: split={rec['mesh'] and rec['mesh'].get('last_split')} "
            f"sources={rec['warm_sources']} batch_pps={rec['batch_pps']}",
            file=sys.stderr, flush=True,
        )
        return rec

    runs = {}
    try:
        for n in counts:
            plane = os.path.join(workdir, f"plane_{n}")
            os.makedirs(plane, exist_ok=True)
            # bake: fresh process compiles + saves the sharded artifacts
            runs[f"n{n}_bake"] = run_child(n, "bake", plane)
            # aot: a SECOND fresh process must cold-start off the store
            runs[f"n{n}_aot"] = run_child(n, "aot", plane)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    # (a) parity: every child — any device count, bake or aot — produced
    # byte-identical solutions for the same corpus
    hashes = {k: r["solution_hash"] for k, r in runs.items()}
    parity = len(set(hashes.values())) == 1
    # (b) split evidence: every multi-device child's dispatches landed on
    # ALL devices (output sharding), never fewer
    split_ok = True
    max_split = 1
    for n in counts:
        if n <= 1:
            continue
        for phase in ("bake", "aot"):
            m = runs[f"n{n}_{phase}"]["mesh"]
            if (
                m is None
                or m["dispatches"] < 1
                or m["last_split"].get("devices") != n
                or m["min_devices_seen"] != n
            ):
                split_ok = False
            else:
                max_split = max(max_split, n)
    # (c) AOT: the second fresh process served every bucket from the
    # store (zero trace-and-compile on the serving ladder)
    aot_ok = all(
        all(
            s is not None and s.startswith("aot:")
            for s in runs[f"n{n}_aot"]["warm_sources"].values()
        )
        and (runs[f"n{n}_aot"]["aot"] or {}).get("loaded", 0) >= 1
        for n in counts
    )
    coalesced_ok = all(
        (r["coalescer"] or {}).get("batches", 0) >= 1 for r in runs.values()
    )

    artifact = {
        "mode": "mesh-scaling",
        "platform": "cpu-fake-devices",
        "smoke": smoke,
        "device_counts": counts,
        "evidence_basis": (
            "fresh --xla_force_host_platform_device_count=N children "
            "(parallel/sim.py): batch-split read from output sharding "
            "metadata, parity as sha256 over the full solution tensor, "
            "AOT cold start as warm sources in a second fresh process; "
            "wall-clock recorded per child but NOT a headline (fake "
            "devices share host cores — see --mode tpu-window)"
        ),
        "parity_across_topologies": parity,
        "batch_split_verified": split_ok,
        "aot_cold_start_verified": aot_ok,
        "coalescer_engaged": coalesced_ok,
        "runs": runs,
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"# mesh artifact: {out_path}", file=sys.stderr, flush=True)
    ok = parity and split_ok and aot_ok and coalesced_ok
    print(
        json.dumps(
            {
                "metric": "mesh_batch_split_devices",
                "value": float(max_split if ok else 0),
                "unit": "devices",
                # acceptance: the largest requested topology verified end
                # to end (split + parity + AOT cold start + coalescer)
                "vs_baseline": round(
                    (max_split if ok else 0) / max(max(counts), 1), 3
                ),
            }
        )
    )
    sys.exit(0 if ok else 4)


def _exit_code(rc: int) -> int:
    """Map a signal-killed child's negative returncode to 128+signal so
    pipeline callers never see it aliased into an unrelated 8-bit code
    (e.g. -9 -> 247); positive codes pass through (ADVICE r3)."""
    return 128 - rc if rc < 0 else rc


def _claim_window_open() -> bool:
    """Cheap TCP probe of the axon relay's terminal ports before spending
    a child attempt: 8082 (claim/init) AND 8093 (remote_compile) must
    accept, or the attempt is guaranteed to hang in init or die mid-
    compile with Connection refused (the round-5 discovery: the relay
    forwards these ports intermittently — window timeline in
    benchmarks/tpu_session_r5.log). Non-axon platforms skip the probe."""
    import socket

    if os.environ.get("JAX_PLATFORMS", "") != "axon":
        return True
    if os.environ.get("BENCH_PLATFORM"):
        return True  # child is rerouted off the axon backend entirely
    if os.environ.get("BENCH_SKIP_PORT_PROBE") == "1":
        return True
    for port in (8082, 8093):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(1.0)
        try:
            s.connect(("127.0.0.1", port))
        except OSError:
            return False
        finally:
            s.close()
    return True


def main_with_retry():
    """Throughput mode wrapped in a bounded probe-retry-fallback loop.

    Backend init on the pooled/tunneled chip can hang (stale pool-side
    claim) or raise UNAVAILABLE (sick terminal) — docs/OPERATIONS.md. Each
    attempt runs in a child process whose own init watchdog fails fast
    (rc=3; the child always exits by its OWN watchdog, never an external
    kill — a mid-compile kill is what wedges the claim in the first place).

    Round 3 showed the remaining hole (BENCH_r03.json: rc=124,
    parsed:null): the retry loop kept burning attempts until the DRIVER's
    outer timeout SIGKILLed it mid-attempt, leaving no JSON line at all.
    So the parent now (a) sizes its default total budget to finish well
    inside a ~30 min driver window, and (b) when the budget no longer fits
    another TPU attempt, runs one final child on the CPU backend (measured
    ~25 s for the 4096-board corpus) so the artifact ALWAYS carries a
    parseable, clearly-labeled record — a claim that never frees produces
    `*_cpu_fallback` + the failure reason instead of parsed:null
    (VERDICT r3 task 1).
    """
    import subprocess

    total = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "1500"))
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT_S", "420"))
    backoff = float(os.environ.get("BENCH_RETRY_BACKOFF_S", "45"))
    # wall reserved for the CPU-fallback child (compile + solve + slack)
    fallback_reserve = float(os.environ.get("BENCH_FALLBACK_RESERVE_S", "150"))
    deadline = time.time() + total
    env = dict(
        os.environ,
        BENCH_CHILD="1",
        BENCH_INIT_TIMEOUT_S=str(init_timeout),
    )
    def run_child(child_env, timeout=None):
        """Run one bench child, forwarding its streams; returns (rc, stdout).

        Stdout is captured and re-printed so the parent KNOWS whether the
        child landed its JSON line — a child that dies post-init (assert,
        OOM kill) with no JSON must route to the fallback, not propagate a
        bare nonzero exit with an empty stdout (the parsed:null shape this
        wrapper exists to prevent). On timeout the child is killed (only
        used for the CPU fallback child, which holds no pooled claim)."""
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=child_env,
                capture_output=True,
                text=True,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            return -9, ""
        if proc.stdout:
            print(proc.stdout, end="", flush=True)
        if proc.stderr:
            print(proc.stderr, end="", file=sys.stderr, flush=True)
        return proc.returncode, proc.stdout

    attempt = 0
    reason = None
    announced_closed = False
    while True:
        # Window scan: while the relay ports are closed, an attempt can
        # only burn init_timeout seconds — wait for a window instead, as
        # long as the budget still fits an attempt + the fallback reserve.
        # ONE probe per iteration: a transient flap routes back here (the
        # scan continues on the remaining budget), never straight to the
        # fallback (code-review r5).
        if not _claim_window_open():
            if (
                deadline - time.time()
                > init_timeout + backoff + fallback_reserve
            ):
                if not announced_closed:
                    print(
                        "# claim window closed (relay ports 8082/8093 not "
                        "accepting) — scanning until it opens or the "
                        "budget forces the fallback",
                        file=sys.stderr,
                        flush=True,
                    )
                    announced_closed = True
                time.sleep(15)
                continue
            reason = (
                f"claim window did not open within the remaining budget "
                f"({attempt} attempts made): axon relay ports 8082/8093 "
                f"refused connections (window timeline: "
                f"benchmarks/tpu_session_r5.log)"
            )
            break
        if announced_closed:
            print("# claim window open — attempting", file=sys.stderr, flush=True)
            announced_closed = False
        attempt += 1
        t0 = time.time()
        rc, out = run_child(env)
        if rc == 0:
            if any(ln.startswith("{") for ln in out.splitlines()):
                sys.exit(0)  # the number landed
            reason = "bench child exited 0 without emitting a JSON line"
            break
        if rc != 3:
            reason = (
                f"bench child failed post-init rc={_exit_code(rc)} "
                f"(claim acquisition succeeded or was skipped)"
            )
            break
        left = deadline - time.time()
        print(
            f"# attempt {attempt} failed claim acquisition after "
            f"{time.time() - t0:.0f}s; budget left {left:.0f}s",
            file=sys.stderr,
            flush=True,
        )
        if left < init_timeout + backoff + fallback_reserve:
            reason = (
                f"pooled-chip claim never freed: {attempt} init attempts of "
                f"{init_timeout:.0f}s each within BENCH_TOTAL_BUDGET_S="
                f"{total:.0f}s (docs/OPERATIONS.md claim discipline)"
            )
            break
        time.sleep(backoff)

    print(
        "# falling back to the CPU backend so the artifact stays "
        "machine-readable",
        file=sys.stderr,
        flush=True,
    )
    # Fallback batch: the committed-corpus size for this board size, unless
    # the caller's (smaller) BENCH_BATCH also has a committed corpus — a
    # batch with NO cached corpus would regenerate unique-solution puzzles
    # on CPU, which can blow through the reserve (code-review r4).
    fb_batch = {9: 4096, 16: 2048, 25: 512}[BENCH_SIZE]
    if BENCH_BATCH < fb_batch and os.path.exists(CORPUS_PATH):
        fb_batch = BENCH_BATCH
    fb_env = dict(
        env,
        BENCH_PLATFORM="cpu",
        BENCH_FALLBACK_REASON=reason,
        BENCH_BATCH=str(fb_batch),
        BENCH_REPEATS="3",
    )
    # The reserve bounds the WHOLE fallback child, or a slow CPU run would
    # reproduce the driver-SIGKILL/parsed:null failure this path exists to
    # prevent. A timeout kill is safe here: the CPU child holds no pooled
    # claim to wedge (docs/OPERATIONS.md discipline applies to accelerator
    # clients only).
    rc, out = run_child(fb_env, timeout=fallback_reserve)
    if rc == -9:
        print("# CPU fallback child exceeded its reserve", file=sys.stderr)
    if rc == 0 and not any(ln.startswith("{") for ln in out.splitlines()):
        # same contract check as the primary child: exit 0 without a JSON
        # line must still produce the last-resort record (code-review r4)
        rc = 1
    if rc != 0:
        # last resort: the parent itself emits the one JSON line — the
        # artifact contract ("every round records something parseable")
        # survives even a broken CPU backend
        print(
            json.dumps(
                {
                    "metric": (
                        f"puzzles_per_sec_per_chip_hard{BENCH_SIZE}x"
                        f"{BENCH_SIZE}_unmeasured"
                    ),
                    "value": 0.0,
                    "unit": "puzzles/s/chip",
                    "vs_baseline": 0.0,
                    "fallback_reason": (
                        f"{reason}; CPU fallback child also failed "
                        f"rc={_exit_code(rc)}"
                    ),
                }
            )
        )
        # rc=3 keeps the give-up visible to pipeline callers keying on the
        # exit code — the *_unmeasured value-0.0 line is a failure record,
        # not a measurement (ADVICE r4)
        sys.exit(3)
    sys.exit(0)


if __name__ == "__main__":
    # mode selection: BENCH_MODE env var (the driver's convention) or the
    # --mode CLI flag (`python bench.py --mode concurrent`); the flag wins.
    # A bare `python bench.py` is byte-for-byte the old throughput path.
    mode = os.environ.get("BENCH_MODE", "throughput")
    argv = sys.argv[1:]
    if "--mode" in argv:
        idx = argv.index("--mode") + 1
        if idx >= len(argv):
            sys.exit("bench.py: --mode needs a value "
                     "(throughput|latency|farm|concurrent|overload|"
                     "coldstart|obs-overhead|hotloop|continuous|cache|"
                     "chaos|tpu-window|mesh-scaling)")
        mode = argv[idx]
    if mode == "latency":
        main_latency()
    elif mode == "chaos":
        main_chaos()
    elif mode == "continuous":
        main_continuous()
    elif mode == "cache":
        main_cache()
    elif mode == "farm":
        main_farm()
    elif mode == "concurrent":
        main_concurrent()
    elif mode == "overload":
        main_overload()
    elif mode == "coldstart":
        main_coldstart()
    elif mode == "coldstart-child":
        main_coldstart_child()
    elif mode == "obs-overhead":
        main_obs_overhead()
    elif mode == "hotloop":
        main_hotloop()
    elif mode == "tpu-window":
        main_tpu_window()
    elif mode == "mesh-scaling":
        main_mesh_scaling()
    elif mode == "mesh-scaling-child":
        main_mesh_scaling_child()
    elif mode != "throughput":
        sys.exit(f"bench.py: unknown mode {mode!r} "
                 f"(throughput|latency|farm|concurrent|overload|coldstart|"
                 f"obs-overhead|hotloop|continuous|cache|chaos|tpu-window|"
                 f"mesh-scaling)")
    elif os.environ.get("BENCH_CHILD") == "1":
        main()
    else:
        main_with_retry()
