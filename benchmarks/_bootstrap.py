"""Shared bootstrap for the scripts in benchmarks/ (ISSUE 7 satellite).

Every experiment / session script in this directory used to open with its
own copy of the same three stanzas; they live here once:

  * repo-root ``sys.path`` insertion — these scripts run as plain files
    (``python benchmarks/exp_*.py``) so the package is not importable
    until the repo root is on the path. Deliberately NOT via PYTHONPATH:
    exporting it breaks this environment's TPU plugin discovery
    (exp_pallas.py, round 2).
  * corpus/artifact path helpers anchored at the repo root, so scripts
    work from any CWD.
  * the TPU persistent-compile-cache env defaults shared with bench.py
    (``setup_compile_cache_env``): a serving-config compile that succeeds
    once in ANY claim window is reused by every later attempt — on the
    tunneled chip, compiles are the scarce resource.

Usage (first import in every benchmarks/ script, before jax)::

    import _bootstrap  # noqa: F401  (repo root now importable)
    from _bootstrap import corpus_path, REPO

Import order note: ``import _bootstrap`` works because Python puts the
script's own directory (benchmarks/) on sys.path entry 0.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

BENCHMARKS = os.path.join(REPO, "benchmarks")


def repo_path(*parts: str) -> str:
    """Absolute path under the repo root."""
    return os.path.join(REPO, *parts)


def corpus_path(name: str) -> str:
    """Absolute path of a cached corpus / artifact in benchmarks/."""
    return os.path.join(BENCHMARKS, name)


def load_corpus(name: str, key: str = "boards"):
    """Load a committed .npz corpus by file name."""
    import numpy as np

    return np.load(corpus_path(name))[key]


def setup_compile_cache_env() -> str:
    """Point jax's persistent compile cache at the shared measurement-
    session cache (bench.py owns the ONE path definition) unless the
    caller already configured one. Returns the directory in effect.
    Must run before jax initializes."""
    from bench import COMPILE_CACHE_DIR  # sys.path set above

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", COMPILE_CACHE_DIR)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    return os.environ["JAX_COMPILATION_CACHE_DIR"]
