"""Probe whether client-side AOT compilation works against the terminal.

The axon platform's normal path compiles terminal-side via
``POST 127.0.0.1:8093/remote_compile`` — a relay-forwarded port that is
frequently closed (round-5 discovery; benchmarks/tpu_session_r5.log). The
plugin also supports ``remote_compile=False``: compile LOCALLY with the
pip-installed libtpu and only execute on the terminal — no 8093
dependency at all. Round 2 found the terminal refused such programs on a
libtpu build mismatch (terminal Nov 2025 vs client Jan 2026); this probe
retests that cheaply each claim window, because the infra has visibly
churned since and a healed mismatch would unlock the whole measurement
session without the flaky compile relay.

MUST be launched with ``PALLAS_AXON_REMOTE_COMPILE=0`` in the
environment (the sitecustomize reads it at interpreter start; setting it
after import is a no-op). The wrapper does this.

Appends one JSON line to tpu_session_r5.jsonl:
  {"phase": "aot_probe_ok", ...}      — local compile + on-chip run WORKED
  {"phase": "aot_probe_error", ...}   — the refusal/diagnostic detail
Exit 0 on success, 3 otherwise.
"""

import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "tpu_session_r5.jsonl")


def emit(record):
    record["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
    print("EMIT", json.dumps(record), flush=True)


def main():
    if os.environ.get("PALLAS_AXON_REMOTE_COMPILE") != "0":
        emit(
            {
                "phase": "aot_probe_error",
                "err": "launched without PALLAS_AXON_REMOTE_COMPILE=0 — "
                "the sitecustomize already registered remote-compile",
            }
        )
        os._exit(3)

    done = threading.Event()

    def watchdog():
        if not done.wait(240):
            emit(
                {
                    "phase": "aot_probe_error",
                    "err": "probe exceeded 240s (init or run hang)",
                }
            )
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()

    try:
        import jax

        devs = jax.devices()
        import jax.numpy as jnp

        t0 = time.perf_counter()
        out = jax.jit(lambda x: (x @ x).sum())(
            jnp.ones((128, 128), jnp.bfloat16)
        )
        val = float(out)
        emit(
            {
                "phase": "aot_probe_ok",
                "platform": devs[0].platform,
                "compile_run_s": round(time.perf_counter() - t0, 2),
                "result": val,
                "detail": "local AOT compile executed on the terminal — "
                "the session can run with PALLAS_AXON_REMOTE_COMPILE=0",
            }
        )
        done.set()
        sys.exit(0)
    except Exception as e:  # noqa: BLE001 — the diagnostic IS the point
        # (not BaseException: the success path's SystemExit(0) must
        # propagate, not be re-reported as failure — code-review r5)
        emit({"phase": "aot_probe_error", "err": repr(e)[:800]})
        done.set()
        os._exit(3)


if __name__ == "__main__":
    main()
