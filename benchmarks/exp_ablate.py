"""Ablate analyze() components to find kernel-level wins at large batch.

Variants (monkeypatched into the solver step):
  base      — current analyze (naked + hidden singles, int32 one-hots)
  int8      — one-hot tensors in int8 (less HBM traffic if materialized)
  naked     — no hidden-singles pass (cheaper sweep, more iterations)
  hid-row   — hidden singles from row totals only (middle ground)
"""

import time

import _bootstrap  # noqa: F401 — repo root onto sys.path
import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import SPEC_9
from sudoku_solver_distributed_tpu.ops import solver as S
from sudoku_solver_distributed_tpu.ops.encode import (
    _counts_to_mask,
    box_index,
)
from sudoku_solver_distributed_tpu.ops.propagate import Analysis

corpus = np.load(_bootstrap.corpus_path("corpus_9x9_hard_4096.npz"))["boards"]
MULT = 4
big = jnp.asarray(np.tile(corpus, (MULT, 1, 1)))
B_TOTAL = big.shape[0]


def make_analyze(onehot_dtype=jnp.int32, hidden="full"):
    def analyze(grid, spec):
        n, N = spec.box, spec.size
        B = grid.shape[0]
        onehot = (
            grid[..., None] == jnp.arange(1, N + 1, dtype=grid.dtype)
        ).astype(onehot_dtype)
        rows = onehot.sum(axis=2)
        cols = onehot.sum(axis=1)
        boxes = onehot.reshape(B, n, n, n, n, N).sum(axis=(2, 4)).reshape(B, N, N)
        dup = (
            (rows > 1).any(axis=(1, 2))
            | (cols > 1).any(axis=(1, 2))
            | (boxes > 1).any(axis=(1, 2))
        )
        solved = (
            (rows == 1).all(axis=(1, 2))
            & (cols == 1).all(axis=(1, 2))
            & (boxes == 1).all(axis=(1, 2))
        )
        shifts = jnp.arange(N, dtype=jnp.int32)
        row_used = _counts_to_mask(rows, spec)
        col_used = _counts_to_mask(cols, spec)
        box_used = _counts_to_mask(boxes, spec)
        bidx = box_index(spec)
        used = row_used[:, :, None] | col_used[:, None, :] | box_used[:, bidx]
        empty = grid == 0
        cand = jnp.where(empty, ~used & jnp.int32(spec.full_mask), jnp.int32(0))

        if hidden == "none":
            hidden_mask = jnp.zeros_like(cand)
        else:
            conehot = (
                jnp.right_shift(cand[..., None], shifts) & 1
            ).astype(onehot_dtype)
            row_tot = conehot.sum(axis=2)
            if hidden == "full":
                col_tot = conehot.sum(axis=1)
                box_tot = (
                    conehot.reshape(B, n, n, n, n, N)
                    .sum(axis=(2, 4))
                    .reshape(B, N, N)
                )
                hid = conehot & (
                    (row_tot[:, :, None, :] == 1)
                    | (col_tot[:, None, :, :] == 1)
                    | (box_tot[:, bidx, :] == 1)
                ).astype(onehot_dtype)
            else:  # row-only
                hid = conehot & (row_tot[:, :, None, :] == 1).astype(onehot_dtype)
            hidden_mask = jnp.left_shift(hid.astype(jnp.int32), shifts).sum(-1)

        naked = jax.lax.population_count(cand) == 1
        assign = jnp.where(naked, cand, hidden_mask)
        assign = assign & -assign
        dead = (empty & (cand == 0)).any(axis=(1, 2))
        bad = ((grid < 0) | (grid > N)).any(axis=(1, 2))
        return Analysis(cand, assign, dup | dead | bad, solved)

    return analyze


def bench(name, analyze_fn, reps=4):
    orig = S.analyze
    S.analyze = analyze_fn
    try:
        f = jax.jit(
            lambda g: (
                lambda r: (r.solved, r.iters)
            )(S.solve_batch(g, SPEC_9, max_depth=64, max_iters=8192))
        )
        out = jax.block_until_ready(f(big))
        assert bool(np.asarray(out[0]).all()), name
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f(big))
            ts.append(time.perf_counter() - t0)
        print(
            f"{name:8s} best={min(ts)*1000:7.1f}ms pps={B_TOTAL/min(ts):9.0f} "
            f"iters={int(out[1])}",
            flush=True,
        )
    finally:
        S.analyze = orig


bench("base", make_analyze())
bench("int8", make_analyze(onehot_dtype=jnp.int8))
bench("naked", make_analyze(hidden="none"))
bench("hid-row", make_analyze(hidden="row"))
