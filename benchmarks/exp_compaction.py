"""Perf experiment: compaction schedule × guess-stack depth on the TPU.

Times solve_batch on the cached hard-9×9 corpus under different compaction
schedules (floor, divisor) and max_depth values. Not part of the test suite;
run manually: python benchmarks/exp_compaction.py
"""

import itertools
import time

import _bootstrap  # noqa: F401 — repo root onto sys.path
import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import SPEC_9, solve_batch
from sudoku_solver_distributed_tpu.ops import solver as S

corpus = np.load(_bootstrap.corpus_path("corpus_9x9_hard_4096.npz"))["boards"]
dev = jnp.asarray(corpus)


EVERY = int(__import__("os").environ.get("EXP_COMPACT_EVERY", "1"))


def schedule(B, div, floor):
    caps = [B]
    while caps[-1] // div >= floor:
        caps.append(caps[-1] // div)
    return caps


def run(caps, max_depth, reps=3):
    def fn(g):
        state = S.init_state(g, SPEC_9, max_depth)
        # PR 7 signature: stats threading + descent-check period K
        state, _ = S._run_compacted(
            state, S._zero_stats(), caps, SPEC_9, 4096, every=EVERY
        )
        state = S.finalize_status(state, SPEC_9)
        return state.grid, state.status, state.iters

    f = jax.jit(fn)
    grid, status, iters = jax.block_until_ready(f(dev))
    assert bool((np.asarray(status) == S.SOLVED).all()), caps
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(dev))
        times.append(time.perf_counter() - t0)
    return min(times), int(iters)


B = corpus.shape[0]
results = []
for (div, floor), depth in itertools.product(
    [(4, 64), (2, 64), (2, 32), (2, 16), (4, 16)], [64, 32, 24]
):
    caps = schedule(B, div, floor)
    t, iters = run(caps, depth)
    pps = B / t
    results.append((pps, div, floor, depth, t, iters))
    print(
        f"div={div} floor={floor:3d} depth={depth:2d} "
        f"best={t*1000:7.1f}ms pps={pps:9.0f} iters={iters}",
        flush=True,
    )

results.sort(reverse=True)
print("\nbest:", results[0])
