"""Focused re-measurement of top compaction configs, more repeats."""

import time

import _bootstrap  # noqa: F401 — repo root onto sys.path
import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import SPEC_9
from sudoku_solver_distributed_tpu.ops import solver as S

corpus = np.load(_bootstrap.corpus_path("corpus_9x9_hard_4096.npz"))["boards"]
dev = jnp.asarray(corpus)


EVERY = int(__import__("os").environ.get("EXP_COMPACT_EVERY", "1"))


def schedule(B, div, floor):
    caps = [B]
    while caps[-1] // div >= floor:
        caps.append(caps[-1] // div)
    return caps


def run(caps, max_depth, reps=10):
    def fn(g):
        state = S.init_state(g, SPEC_9, max_depth)
        # PR 7 signature: stats threading + descent-check period K
        state, _ = S._run_compacted(
            state, S._zero_stats(), caps, SPEC_9, 4096, every=EVERY
        )
        state = S.finalize_status(state, SPEC_9)
        return state.grid, state.status, state.iters

    f = jax.jit(fn)
    grid, status, iters = jax.block_until_ready(f(dev))
    assert bool((np.asarray(status) == S.SOLVED).all()), caps
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(dev))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times


B = corpus.shape[0]
for div, floor, depth in [
    (4, 64, 64),
    (4, 64, 24),
    (2, 32, 24),
    (2, 16, 24),
    (2, 32, 32),
    (2, 64, 24),
]:
    t = run(schedule(B, div, floor), depth)
    print(
        f"div={div} floor={floor:3d} depth={depth:2d} "
        f"min={t[0]*1000:7.1f}ms p50={t[len(t)//2]*1000:7.1f}ms "
        f"max={t[-1]*1000:7.1f}ms pps={B/t[0]:9.0f}",
        flush=True,
    )
