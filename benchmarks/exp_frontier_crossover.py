"""Measure the bucket-path vs frontier-race crossover (VERDICT r3 task 3).

Where does the mesh race (parallel/frontier.py) actually WIN? For each board
in the adversarial deep-search corpus (benchmarks/make_adversarial.py) and a
control slice of the ordinary hard corpus, measure per board:

  * bucket  — blocking single-board solve on the serving bucket path
              (bucket 1, waves_eff=1, full iteration budget);
  * race    — ``frontier_solve`` on the default mesh (states_per_device
              as served);
  * iters   — the board's lockstep iteration count (platform-independent
              difficulty, what the auto-route probe actually observes).

Output: a per-decile table of (iters, guesses, bucket_ms, race_ms) + the
measured crossover in LOCKSTEP ITERATIONS — the unit the auto-route probe
actually observes — i.e. the smallest per-board iteration count from which
the race consistently beats the bucket path. That number justifies (or
corrects) ``SolverEngine(frontier_escalate_iters=...)`` (default 512).

Platform note: on the virtual CPU mesh the 8 shards serialize on one core,
so race_ms is pessimistic there; run on real hardware for the serving
decision (benchmarks/tpu_session.py carries a phase for it). Iteration
counts are platform-independent either way.
"""

import json
import os
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path

STATES = int(os.environ.get("XO_STATES", "64"))
CONTROL = int(os.environ.get("XO_CONTROL", "32"))
REPS = int(os.environ.get("XO_REPS", "3"))
SIZE = int(os.environ.get("XO_SIZE", "9"))  # 16: hexadoku crossover table
_CONTROL_CORPUS = {
    9: "corpus_9x9_hard_4096.npz",
    16: "corpus_16x16_hard_2048.npz",
    25: "corpus_25x25_hard_512.npz",
}


def main():
    import jax

    if os.environ.get("XO_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["XO_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.engine import SolverEngine
    from sudoku_solver_distributed_tpu.parallel import (
        default_mesh,
        frontier_solve,
    )

    # deepest available adversarial corpus, in preference order: the
    # multi-run union (benchmarks/merge_deep.py — round 4, what makes the
    # boundary more than one-seed-lucky), the hill-climbed set, any
    # annealing-mined corpus (KEEP-size agnostic), else the random-minimal
    # harvest
    import glob as _glob

    candidates = [
        os.path.join(REPO, "benchmarks", f"corpus_{SIZE}x{SIZE}_deep_union.npz"),
        os.path.join(REPO, "benchmarks", f"corpus_{SIZE}x{SIZE}_deep_128.npz"),
        *sorted(
            _glob.glob(
                os.path.join(
                    REPO, "benchmarks", f"corpus_{SIZE}x{SIZE}_deep_anneal_*.npz"
                )
            ),
            reverse=True,  # larger KEEP first
        ),
        os.path.join(
            REPO, "benchmarks", f"corpus_{SIZE}x{SIZE}_adversarial_128.npz"
        ),
    ]
    adv_path = next((p for p in candidates if os.path.exists(p)), None)
    if adv_path is None:
        sys.exit(
            f"no deep/adversarial corpus for size {SIZE} — run "
            f"MINE_SIZE={SIZE} benchmarks/mine_deep_anneal.py first"
        )
    adv = np.load(adv_path)
    adv_boards = adv["boards"]
    adv_limit = int(os.environ.get("XO_ADV_LIMIT", "0"))
    if adv_limit:
        adv_boards = adv_boards[:adv_limit]  # smoke runs
    if SIZE not in _CONTROL_CORPUS:
        sys.exit(
            f"XO_SIZE={SIZE} unsupported; have {sorted(_CONTROL_CORPUS)}"
        )
    hard = np.load(
        os.path.join(REPO, "benchmarks", _CONTROL_CORPUS[SIZE])
    )["boards"][:CONTROL]
    boards = np.concatenate([hard, adv_boards])
    print(f"# adversarial corpus: {os.path.basename(adv_path)}", file=sys.stderr)

    from sudoku_solver_distributed_tpu.ops import spec_for_size

    spec = spec_for_size(SIZE)
    mesh = default_mesh()
    eng = SolverEngine(spec, buckets=(1,))  # plain bucket path, serving config
    eng.warmup()

    race_kw = dict(
        states_per_device=STATES,
        locked=eng.locked_candidates,
        waves=eng.waves,
        max_depth=eng.max_depth,
        naked_pairs=eng.naked_pairs,
    )
    # warm the race on the first board
    frontier_solve(boards[-1], mesh, spec, **race_kw)

    # per-board lockstep iterations under the exact bucket-1 serving view
    # (waves_eff=1) — the quantity the auto-route probe compares against
    # frontier_escalate_iters; a (1,N,N) solve's res.iters IS that board's
    # count (no batch mixing)
    from sudoku_solver_distributed_tpu.ops import (
        serving_config,
        solve_batch,
    )

    iters_cfg = dict(serving_config(SIZE), waves=1)
    iters_solve = jax.jit(lambda g: solve_batch(g, spec, **iters_cfg))

    def board_iters(board):
        res = jax.block_until_ready(iters_solve(jnp.asarray(board[None])))
        return int(res.iters)

    rows = []
    for k, board in enumerate(boards):
        bucket_ms = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            sol, info = eng.solve_one(board, frontier=False)
            bucket_ms.append((time.perf_counter() - t0) * 1e3)
        race_ms = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            rsol, rinfo = frontier_solve(board, mesh, spec, **race_kw)
            race_ms.append((time.perf_counter() - t0) * 1e3)
        assert (sol is None) == (rsol is None), f"verdict mismatch board {k}"
        rows.append(
            {
                "k": k,
                "cls": "hard" if k < len(hard) else "adv",
                "clues": int((board > 0).sum()),
                "guesses": int(info["guesses"]),
                "iters": board_iters(board),
                "bucket_ms": round(min(bucket_ms), 2),
                "race_ms": round(min(race_ms), 2),
            }
        )
        if k % 16 == 0:
            print(f"# {k + 1}/{len(boards)}", file=sys.stderr, flush=True)

    # difficulty axis: per-board lockstep iterations (what the probe sees)
    rows.sort(key=lambda r: r["iters"])
    wins = [r for r in rows if r["race_ms"] < r["bucket_ms"]]
    # Crossover: the smallest iteration level L (scanning GROUP boundaries
    # only — a split inside a run of equal values would verify fractions no
    # iters-based policy can reproduce) where the race wins >=60% of boards
    # at-or-above L and <40% below. If the race wins everywhere (expected
    # on a big mesh), the first group's level is the honest answer, not
    # None.
    crossover = None
    win = lambda t: t["race_ms"] < t["bucket_ms"]  # noqa: E731
    if rows and sum(map(win, rows)) / len(rows) >= 0.95:
        crossover = rows[0]["iters"]
    else:
        for i in range(1, len(rows)):
            if rows[i]["iters"] == rows[i - 1]["iters"]:
                continue  # group boundary only
            above, below = rows[i:], rows[:i]
            fa = sum(map(win, above)) / len(above)
            fb = sum(map(win, below)) / len(below)
            if win(above[0]) and fa >= 0.6 and fb < 0.4:
                crossover = above[0]["iters"]
                break

    deciles = []
    for d in range(10):
        sl = rows[len(rows) * d // 10 : len(rows) * (d + 1) // 10]
        if not sl:
            continue
        deciles.append(
            {
                "iters_range": [sl[0]["iters"], sl[-1]["iters"]],
                "guesses_range": [sl[0]["guesses"], sl[-1]["guesses"]],
                "bucket_ms_p50": round(
                    float(np.median([r["bucket_ms"] for r in sl])), 2
                ),
                "race_ms_p50": round(
                    float(np.median([r["race_ms"] for r in sl])), 2
                ),
                "race_wins": sum(r["race_ms"] < r["bucket_ms"] for r in sl),
                "n": len(sl),
            }
        )
    print(
        json.dumps(
            {
                "size": SIZE,
                "platform": jax.default_backend(),
                "mesh_devices": int(mesh.devices.size),
                "states_per_device": STATES,
                "boards": len(rows),
                "race_wins_total": len(wins),
                "crossover_iters": crossover,
                "deciles": deciles,
                "rows": rows,
            },
            indent=2,
        )
    )


if __name__ == "__main__":
    main()
