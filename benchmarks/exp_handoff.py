"""Measure probe→race handoff vs restart-from-root (VERDICT r3 task 6).

Under ``frontier_route="auto"`` an escalated board used to pay twice: the
512-iteration probe, then a race that restarted from the ROOT (re-paying
propagation + seeding). The handoff path (engine.frontier_handoff,
parallel/frontier.state_handoff_frontier) seeds the race from the probe's
unexplored subtrees instead. This experiment measures both END-TO-END
``solve_one`` paths on the deep corpus — what an escalated /solve actually
pays — plus the ordinary-hard control slice (which never escalates, so both
paths must tie there).

Output: per-class p50/p95 of both paths + the win rate, appended as one
JSON line to ``benchmarks/handoff_cpu_r4.json``. The serving default
(``SolverEngine(frontier_handoff=...)``) cites this artifact.

Platform note: the virtual CPU mesh serializes shards on one core, so BOTH
race paths are pessimistic vs real hardware equally; the handoff-vs-root
DELTA is the probe's device time + seeding work, which the CPU measurement
captures. benchmarks/tpu_session.py phase 2b carries the on-chip version.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python benchmarks/exp_handoff.py
"""

import json
import os
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path

REPS = int(os.environ.get("HO_REPS", "3"))
N_DEEP = int(os.environ.get("HO_DEEP", "48"))
N_CONTROL = int(os.environ.get("HO_CONTROL", "16"))


def main():
    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("HO_PLATFORM", "cpu")
    )

    import numpy as np

    from sudoku_solver_distributed_tpu.engine import SolverEngine
    from sudoku_solver_distributed_tpu.models import oracle_solve
    from sudoku_solver_distributed_tpu.parallel import default_mesh

    deep_path = os.path.join(REPO, "benchmarks", "corpus_9x9_deep_union.npz")
    if not os.path.exists(deep_path):
        deep_path = os.path.join(REPO, "benchmarks", "corpus_9x9_deep_128.npz")
    deep = np.load(deep_path)["boards"][:N_DEEP]
    hard = np.load(
        os.path.join(REPO, "benchmarks", "corpus_9x9_hard_4096.npz")
    )["boards"][:N_CONTROL]

    mesh = default_mesh()
    engines = {}
    for handoff in (True, False):
        eng = SolverEngine(
            buckets=(1,),
            frontier_mesh=mesh,
            frontier_states_per_device=64,
            frontier_handoff=handoff,
        )
        eng.warmup()
        engines[handoff] = eng
    # warm both escalation paths end-to-end (racer rungs the deep corpus hits)
    for handoff, eng in engines.items():
        eng.solve_one(deep[0])

    def run_class(boards, verify=False):
        rows = []
        for board in boards:
            times = {}
            sols = {}
            for handoff, eng in engines.items():
                best = float("inf")
                for _ in range(REPS):
                    t0 = time.perf_counter()
                    sol, info = eng.solve_one(board)
                    best = min(best, (time.perf_counter() - t0) * 1e3)
                times[handoff] = best
                sols[handoff] = sol
            row = {
                "handoff_ms": round(times[True], 2),
                "root_ms": round(times[False], 2),
                "agree": (sols[True] is None) == (sols[False] is None),
            }
            if verify and sols[True] is not None:
                row["oracle_ok"] = sols[True] == oracle_solve(
                    np.asarray(board).tolist()
                )
            rows.append(row)
        return rows

    deep_rows = run_class(deep, verify=True)
    ctl_rows = run_class(hard)

    def summarize(rows):
        h = np.asarray([r["handoff_ms"] for r in rows])
        r = np.asarray([r["root_ms"] for r in rows])
        return {
            "n": len(rows),
            "handoff_p50_ms": round(float(np.percentile(h, 50)), 2),
            "root_p50_ms": round(float(np.percentile(r, 50)), 2),
            "handoff_p95_ms": round(float(np.percentile(h, 95)), 2),
            "root_p95_ms": round(float(np.percentile(r, 95)), 2),
            "handoff_wins": int((h < r).sum()),
            "speedup_p50": round(
                float(np.percentile(r, 50) / np.percentile(h, 50)), 3
            ),
        }

    record = {
        "experiment": "probe_handoff_vs_root_restart",
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "corpus": os.path.basename(deep_path),
        "reps": REPS,
        "deep": summarize(deep_rows),
        "control_hard": summarize(ctl_rows),
        "all_verdicts_agree": all(
            r["agree"] for r in deep_rows + ctl_rows
        ),
        "oracle_ok": all(r.get("oracle_ok", True) for r in deep_rows),
        "t": round(time.time(), 1),
    }
    out = os.path.join(REPO, "benchmarks", "handoff_cpu_r4.json")
    with open(out, "a") as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record, indent=1))


if __name__ == "__main__":
    main()
