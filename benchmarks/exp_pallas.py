"""Pallas VMEM-resident kernel vs the XLA compacted solver, on real TPU.

Run (needs the tunneled chip): python benchmarks/exp_pallas.py
(sys.path bootstrap below — PYTHONPATH breaks this environment's TPU
plugin discovery, so don't set it.)

Status 2026-07-29 (round 2): the transposed-layout kernel lowers through
Mosaic cleanly (no more "unsupported shape cast"), but this environment
cannot finish the TPU compile for Pallas custom-calls:
  * remote compile (PALLAS_AXON_REMOTE_COMPILE=1): the terminal-side
    tpu_compile_helper exits 1 — its env carries literal warning text in
    TPU_ACCELERATOR_TYPE/TPU_WORKER_HOSTNAMES ("Failed to find host bounds
    for accelerator type: WARNING: could not determine ..."); the helper
    runs env_clear'd server-side, so no client env can fix it.
  * client AOT (PALLAS_AXON_REMOTE_COMPILE=0): refused on a libtpu build
    mismatch (terminal cl/831091709 Nov 12 2025 vs client cl/854318611
    Jan 12 2026); no matching libtpu exists in the image.
Plain XLA programs are unaffected (bench.py compiles and runs). When the
infra allows Mosaic custom-calls, this script produces the comparison.
"""

import os
import sys
import time

import _bootstrap  # noqa: F401 — repo root onto sys.path

import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import SPEC_9, solve_batch
from sudoku_solver_distributed_tpu.ops.pallas_solver import solve_batch_pallas

boards = np.load(
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "corpus_9x9_hard_16384.npz")
)["boards"]
dev = jnp.asarray(boards)
B = dev.shape[0]


def sustained(f, reps=5):
    out = jax.block_until_ready(f(dev))
    t0 = time.perf_counter()
    outs = [f(dev) for _ in range(reps)]
    jax.block_until_ready(outs[-1])
    return (time.perf_counter() - t0) / reps, out


f_xla = jax.jit(lambda g: solve_batch(g, SPEC_9, max_depth=64).status)
t, st = sustained(f_xla)
assert bool((np.asarray(st) == 1).all())
print(f"xla          sustained={t*1000:7.1f}ms pps={B/t:9.0f}", flush=True)

for block in (128, 256, 512):
    f_p = jax.jit(
        lambda g, block=block: solve_batch_pallas(
            g, SPEC_9, block=block, max_depth=64
        ).status
    )
    try:
        t, st = sustained(f_p)
        ok = bool((np.asarray(st) == 1).all())
        print(
            f"pallas b={block:4d} sustained={t*1000:7.1f}ms pps={B/t:9.0f} "
            f"all_solved={ok}",
            flush=True,
        )
    except Exception as e:
        print(f"pallas b={block}: FAIL {type(e).__name__}: {str(e)[:300]}")
