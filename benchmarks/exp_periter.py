"""Measure per-iteration solver cost vs batch size, and iters per level."""

import time

import _bootstrap  # noqa: F401 — repo root onto sys.path
import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import SPEC_9
from sudoku_solver_distributed_tpu.ops import solver as S

corpus = np.load(_bootstrap.corpus_path("corpus_9x9_hard_4096.npz"))["boards"]

# fixed-iteration run: cost per iteration at batch B
for B in [64, 256, 1024, 4096]:
    boards = jnp.asarray(corpus[:B])

    def fn(g, iters):
        st = S.init_state(g, SPEC_9, 64)

        def cond(s):
            return s.iters < iters

        return jax.lax.while_loop(cond, lambda s: S._step(s, SPEC_9), st).grid

    f = jax.jit(fn, static_argnums=1)
    jax.block_until_ready(f(boards, 10))
    jax.block_until_ready(f(boards, 210))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(f(boards, 10))
        t1 = time.perf_counter()
        jax.block_until_ready(f(boards, 210))
        t2 = time.perf_counter()
        ts.append((t2 - t1) - (t1 - t0))  # 200 extra iters, launch cost cancelled
    per_iter = min(ts) / 200
    print(f"B={B:5d}  per-iter={per_iter*1e6:8.1f}us", flush=True)

# iteration count per compaction level (how deep is the tail?)
dev = jnp.asarray(corpus)


def levels(g):
    st = S.init_state(g, SPEC_9, 64)
    marks = []
    for cap in [1024, 256, 64, 0]:
        def cond(s, cap=cap):
            return ((s.status == S.RUNNING).sum() > cap) & (s.iters < 4096)

        st = jax.lax.while_loop(cond, lambda s: S._step(s, SPEC_9), st)
        marks.append(st.iters)
        perm = jnp.argsort((~(st.status == S.RUNNING)).astype(jnp.int32), stable=True)
        st = S._take_boards(st, perm)  # keep full size; just reorder
    return tuple(marks)


marks = jax.jit(levels)(dev)
print("iters at level boundaries (1024/256/64/done):", [int(m) for m in marks])
