"""Separate tunnel RTT from device compute; measure pipelined throughput.

(a) chain K independent solves of the same 4096 corpus inside ONE jit call
    (lax.map) — wall time = RTT + K * compute;
(b) async-dispatch R separate solve calls, blocking only at the end — the
    serving-shaped throughput measurement (dispatch pipelining hides RTT).
"""

import time

import _bootstrap  # noqa: F401 — repo root onto sys.path
import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import SPEC_9, solve_batch

corpus = np.load(_bootstrap.corpus_path("corpus_9x9_hard_4096.npz"))["boards"]
B = corpus.shape[0]
dev = jnp.asarray(corpus)

# (a) K chained solves in one call
for K in [1, 4]:
    stacked = jnp.broadcast_to(dev, (K, *dev.shape))

    def fn(gs):
        res = jax.lax.map(lambda g: solve_batch(g, SPEC_9, max_depth=64), gs)
        return res.solved

    f = jax.jit(fn)
    jax.block_until_ready(f(stacked))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = jax.block_until_ready(f(stacked))
        ts.append(time.perf_counter() - t0)
    assert bool(np.asarray(out).all())
    print(f"chained K={K}: best={min(ts)*1000:7.1f}ms", flush=True)

# (b) pipelined async dispatch of R calls
solve = jax.jit(lambda g: solve_batch(g, SPEC_9, max_depth=64).solved)
jax.block_until_ready(solve(dev))
for R in [1, 4, 16]:
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [solve(dev) for _ in range(R)]
        jax.block_until_ready(outs[-1])
        ts.append(time.perf_counter() - t0)
    best = min(ts)
    print(
        f"pipelined R={R:2d}: total={best*1000:7.1f}ms "
        f"throughput={R*B/best:9.0f} puzzles/s",
        flush=True,
    )
