"""Per-board probe-view sweep distribution per board size.

Answers "is the 512-iteration escalation default size-safe?": for each
committed hard corpus, solve every board under the auto-route probe's exact
view (serving config, waves=1 — what ``SolverEngine._solve_quick`` runs)
and report the per-board sweep distribution. A board whose sweep count
exceeds ``frontier_escalate_iters`` would escalate to the race; ordinary
boards must not (the race loses on them — xo_union_r4.json).

Appends one JSON record per run to ``benchmarks/probe_sweeps_r4.json``.
Run on CPU: ``python benchmarks/exp_probe_sweeps.py``.
"""

import json
import os
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path

CORPORA = {
    9: "corpus_9x9_hard_4096.npz",
    16: "corpus_16x16_hard_2048.npz",
    25: "corpus_25x25_hard_512.npz",
}


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.ops import (
        serving_config,
        solve_batch,
        spec_for_size,
    )

    record = {"experiment": "probe_view_sweeps_per_board", "sizes": {}}
    for size, fname in CORPORA.items():
        boards = np.load(os.path.join(REPO, "benchmarks", fname))["boards"]
        spec = spec_for_size(size)
        cfg = dict(serving_config(size), waves=1)  # the probe's exact view
        solve = jax.jit(lambda g, spec=spec, cfg=cfg: solve_batch(g, spec, **cfg))
        res = jax.block_until_ready(solve(jnp.asarray(boards)))
        assert bool(np.asarray(res.solved).all()), f"unsolved at size {size}"
        sweeps = np.asarray(res.validations)  # per-board: sweeps while active
        qs = np.percentile(sweeps, [50, 90, 95, 99, 100]).astype(int)
        record["sizes"][size] = {
            "corpus": fname,
            "n": int(len(sweeps)),
            "p50": int(qs[0]),
            "p90": int(qs[1]),
            "p95": int(qs[2]),
            "p99": int(qs[3]),
            "max": int(qs[4]),
            "over_512": int((sweeps > 512).sum()),
        }
        print(size, record["sizes"][size])
    record["t"] = round(time.time(), 1)
    with open(
        os.path.join(REPO, "benchmarks", "probe_sweeps_r4.json"), "a"
    ) as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
