"""Solver-config sweep on the bench corpus: one process, one TPU claim.

Usage (from the repo root; each config is a Python-literal dict of
``solve_batch`` keyword overrides):

    python benchmarks/exp_sweep.py \
        "{'max_depth': (32, 81), 'waves': 3}" \
        "{'max_depth': (24, 81), 'waves': 3}"

With no arguments, runs the current bench default, its light-waves
variants (singles-only extra sweeps), and shallower/deeper first-stage
depths.

All configs run sequentially inside this single process so the tunneled
chip is claimed once and the compile cache is shared — do NOT launch
several of these concurrently, and do not wrap in a tight ``timeout``: a
killed mid-compile process wedges the pool-side claim for minutes
(ROADMAP, round-1/2 incidents). Sustained timing matches bench.py:
back-to-back async dispatch, one trailing sync.
"""

import ast
import os
import sys
import time

import _bootstrap  # noqa: F401 — repo root onto sys.path

import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import solve_batch, spec_for_size

SIZE = int(os.environ.get("BENCH_SIZE", "9"))
_DEFAULT_BATCH = {9: 16384, 16: 2048, 25: 128}
REPEATS = int(os.environ.get("BENCH_REPEATS", "5"))

# CPU-measured iteration counts (hard-9×9 corpus, platform-independent):
# full-analysis waves=3 → 238; light waves=3/4/5/6 → 244/220/208/206.
# The TPU question is wall-clock per iteration for each.
DEFAULTS = [
    {"max_depth": (32, 81), "waves": 3, "locked_candidates": True},
    {"max_depth": (32, 81), "waves": 3, "light_waves": True},
    {"max_depth": (32, 81), "waves": 4, "light_waves": True},
    {"max_depth": (32, 81), "waves": 5, "light_waves": True},
    {"max_depth": (24, 81), "waves": 4, "light_waves": True},
]


def main():
    spec = spec_for_size(SIZE)
    batch = _DEFAULT_BATCH[SIZE]
    corpus = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"corpus_{SIZE}x{SIZE}_hard_{batch}.npz",
    )
    boards = np.load(corpus)["boards"]
    dev = jnp.asarray(boards)
    B = boards.shape[0]

    configs = (
        [ast.literal_eval(a) for a in sys.argv[1:]]
        if len(sys.argv) > 1
        else DEFAULTS
    )
    for cfg in configs:
        kw = {"locked_candidates": True, **cfg}
        f = jax.jit(lambda g, kw=kw: solve_batch(g, spec, max_iters=65536, **kw))
        r = jax.block_until_ready(f(dev))
        assert bool(np.asarray(r.solved).all()), f"unsolved boards under {cfg}"
        t0 = time.perf_counter()
        outs = [f(dev) for _ in range(REPEATS)]
        jax.block_until_ready(outs[-1])
        sus = (time.perf_counter() - t0) / REPEATS
        print(
            f"{cfg}  sustained={sus * 1000:.1f}ms  pps={B / sus:,.0f}  "
            f"iters={int(r.iters)}",
            flush=True,
        )


if __name__ == "__main__":
    main()
