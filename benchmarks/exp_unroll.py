"""Perf experiment: unroll K solver steps per while_loop iteration.

The compacted tail runs hundreds of iterations on tiny (64-board) slices where
per-iteration overhead dominates; unrolling amortizes it. Steps on finished
boards are no-ops, so semantics are unchanged.
"""

import sys
import time

import _bootstrap  # noqa: F401 — repo root onto sys.path
import jax
import jax.numpy as jnp
import numpy as np

from sudoku_solver_distributed_tpu.ops import SPEC_9
from sudoku_solver_distributed_tpu.ops import solver as S

corpus = np.load(_bootstrap.corpus_path("corpus_9x9_hard_4096.npz"))["boards"]
dev = jnp.asarray(corpus)


def run_unrolled(caps, unroll, max_depth=64, max_iters=4096, reps=8):
    def loop(state, cap_next):
        def cond(s):
            running = (s.status == S.RUNNING).sum()
            lo = cap_next if cap_next else 0
            return (s.iters < max_iters) & (running > lo)

        def body(s):
            for _ in range(unroll):
                s = S._step(s, SPEC_9)
            return s

        return jax.lax.while_loop(cond, body, state)

    def fn(g):
        state = S.init_state(g, SPEC_9, max_depth)
        # replicate _run_compacted but with unrolled bodies
        def rec(state, caps):
            if len(caps) == 1:
                return loop(state, 0)
            state = loop(state, caps[1])
            perm = jnp.argsort(
                (~(state.status == S.RUNNING)).astype(jnp.int32), stable=True
            )
            inv = jnp.argsort(perm)
            permuted = S._take_boards(state, perm)
            sub = jax.tree.map(
                lambda x: x[: caps[1]] if x.ndim else x, permuted
            )
            sub = rec(sub, caps[1:])
            merged = S._write_boards(permuted, sub, caps[1])
            return S._take_boards(merged, inv)

        state = rec(state, caps)
        state = S.finalize_status(state, SPEC_9)
        return state.grid, state.status, state.iters

    f = jax.jit(fn)
    grid, status, iters = jax.block_until_ready(f(dev))
    assert bool((np.asarray(status) == S.SOLVED).all())
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(dev))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times, int(iters)


B = corpus.shape[0]
caps = [4096, 1024, 256, 64]
for unroll in [1, 2, 4, 8]:
    t, iters = run_unrolled(caps, unroll)
    print(
        f"unroll={unroll} min={t[0]*1000:7.1f}ms p50={t[len(t)//2]*1000:7.1f}ms "
        f"pps={B/t[0]:9.0f} iters={iters}",
        flush=True,
    )
