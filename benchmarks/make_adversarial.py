"""Build the adversarial deep-search 9×9 corpus (VERDICT r3 task 3).

The frontier race (parallel/frontier.py) exists for boards whose serial DFS
tail dwarfs the race's seeding/collective overhead — the analog of the
reference's distributed dispatch existing to beat its local solve
(reference node.py:427-475). The committed hard corpus averages ~1 guess
per board under the serving config (locked sets + waves), so nothing in it
can ever make the race win; this script mines the generator for the deep
tail instead:

  1. generate certified-unique minimal-ish puzzles (blank-down, ~21-28
     clues) across many seeds;
  2. solve them all with the serving-config XLA solver on CPU and rank by
     per-board guesses (platform-independent difficulty);
  3. keep the top slice as ``corpus_9x9_adversarial_{K}.npz`` with the
     guess counts stored alongside.

Run on CPU (no TPU claim): ``python benchmarks/make_adversarial.py``.
"""

import json
import os
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path

CANDIDATES = int(os.environ.get("ADV_CANDIDATES", "4096"))
KEEP = int(os.environ.get("ADV_KEEP", "128"))
HOLES = int(os.environ.get("ADV_HOLES", "64"))  # upper bound; unique caps it
SEED = int(os.environ.get("ADV_SEED", "20260730"))
CHUNK = 1024


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch
    from sudoku_solver_distributed_tpu.ops import (
        SPEC_9,
        serving_config,
        solve_batch,
    )

    cfg = serving_config(9)
    solve = jax.jit(lambda g: solve_batch(g, SPEC_9, **cfg))

    boards_all, guesses_all = [], []
    t0 = time.time()
    for k in range(0, CANDIDATES, CHUNK):
        n = min(CHUNK, CANDIDATES - k)
        boards = generate_batch(n, HOLES, seed=SEED + k, unique=True)
        res = jax.block_until_ready(solve(jnp.asarray(boards)))
        assert bool(np.asarray(res.solved).all()), "unsolved candidate?!"
        boards_all.append(boards)
        guesses_all.append(np.asarray(res.guesses))
        print(
            f"# {k + n}/{CANDIDATES} candidates, {time.time() - t0:.0f}s",
            flush=True,
        )
    boards = np.concatenate(boards_all)
    guesses = np.concatenate(guesses_all)

    order = np.argsort(-guesses)
    top = order[:KEEP]
    out = os.path.join(REPO, "benchmarks", f"corpus_9x9_adversarial_{KEEP}.npz")
    np.savez_compressed(
        out, boards=boards[top], guesses=guesses[top]
    )
    clues = (boards[top] > 0).sum(axis=(1, 2))
    print(
        json.dumps(
            {
                "kept": KEEP,
                "candidates": CANDIDATES,
                "guesses_max": int(guesses.max()),
                "guesses_p50_kept": float(np.percentile(guesses[top], 50)),
                "guesses_min_kept": int(guesses[top].min()),
                "clues_min": int(clues.min()),
                "clues_p50": float(np.percentile(clues, 50)),
                "corpus": os.path.basename(out),
                "elapsed_s": round(time.time() - t0, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
