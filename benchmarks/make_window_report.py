"""Summarize the round's TPU claim attempts into one machine-readable
artifact (VERDICT r4 task 1's fallback deliverable: "a session artifact
proving N attempts with captured per-attempt error detail").

Parses ``tpu_session_r5.log`` (wrapper attempt markers + window
open/close transitions) and ``tpu_session_r5.jsonl`` (per-phase emits,
init/phase diagnostics) into ``window_report_r5.json``.

Run any time; idempotent:  python benchmarks/make_window_report.py [round]
"""

import json
import os
import re
import time

HERE = os.path.dirname(os.path.abspath(__file__))
# round number as argv[1] (default 5) so next round reuses this parser
# instead of forking an _r6 copy (code-review r5)
import sys

ROUND = int(sys.argv[1]) if len(sys.argv) > 1 else 5
LOG = os.path.join(HERE, f"tpu_session_r{ROUND}.log")
JSONL = os.path.join(HERE, f"tpu_session_r{ROUND}.jsonl")
OUT = os.path.join(HERE, f"window_report_r{ROUND}.json")


def main():
    attempts = []
    windows = []
    cur = None
    for line in open(LOG, errors="replace"):
        m = re.match(
            r"=== attempt (\d+)(?: \(([\w-]+)\))? (\d\d:\d\d:\d\d) ===", line
        )
        if m:
            cur = {"attempt": int(m.group(1)), "start_utc": m.group(3)}
            if m.group(2):
                cur["mode"] = m.group(2)  # AOT | remote-compile
            attempts.append(cur)
            continue
        m = re.match(
            r"=== attempt (\d+) exited rc=(\d+) after (\d+)s (\d\d:\d\d:\d\d)",
            line,
        )
        if m and cur and cur["attempt"] == int(m.group(1)):
            cur.update(
                rc=int(m.group(2)),
                duration_s=int(m.group(3)),
                end_utc=m.group(4),
            )
            continue
        m = re.match(r"=== window (OPEN|CLOSED)[^=]*?(\d\d:\d\d:\d\d)", line)
        if m:
            windows.append({"state": m.group(1), "utc": m.group(2)})

    phases = []
    for line in open(JSONL, errors="replace"):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("phase") in (
            "backend_up",
            "init_timeout",
            "init_error",
            "phase_timeout",
            "error",
            "measure",
            "artifact",
            "pallas_result",
            "pallas_error",
            "done",
        ):
            phases.append(rec)

    # derive the narrative from the parsed records so a re-run never
    # contradicts its own data (code-review r5)
    inits = [p for p in phases if p["phase"] == "backend_up"]
    fails = [
        p
        for p in phases
        if p["phase"] in ("init_timeout", "init_error", "phase_timeout", "error")
    ]
    measures = [p for p in phases if p["phase"] == "measure"]
    done = [p for p in phases if p["phase"] == "done"]
    scanner_stopped = any(
        "scanner stopped at deadline" in ln or "session finished" in ln
        for ln in open(LOG, errors="replace")
    )
    notes = (
        "axon terminal services are relay-forwarded local ports (8082 "
        "claim/init, 8093 remote_compile) that open and close; the "
        "wrapper scans both and launches only on open windows. "
        f"{len(attempts)} attempt(s): {len(inits)} reached backend_up, "
        f"{len(measures)} landed measurements, {len(fails)} recorded "
        f"failure diagnostics (detail in session_events). "
        + (
            "Session finished."
            if done
            else "Scanner stopped at its deadline (claim left free for "
            "the driver's end-of-round bench)."
            if scanner_stopped
            else "Session/scan still running."
        )
    )
    report = {
        "round": ROUND,
        "generated_utc": time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime()),
        "attempts": attempts,
        "n_attempts": len(attempts),
        "window_transitions": windows,
        "session_events": phases,
        "notes": notes,
    }
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
    print(
        f"wrote {OUT}: {len(attempts)} attempts, "
        f"{len(windows)} window transitions, {len(phases)} session events"
    )


if __name__ == "__main__":
    main()
