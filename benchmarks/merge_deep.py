"""Union the deep-board corpora into ``corpus_9x9_deep_union.npz``.

VERDICT r3 task 5: the routing boundary must rest on more than one mining
run. This merges every ``corpus_9x9_deep*.npz`` (the round-3 hill-climb,
the round-4 second-seed hill-climb, the round-4 annealing miner), dedups,
re-scores everything under the probe configuration (serving config,
waves=1) but with the FULL 65536-iteration budget — the deepest mined
boards exceed serving's 4096-iteration first stage, so the stored
``sweeps`` are true per-board totals, NOT probe-comparable against the
serving cap — and keeps the deepest KEEP boards.

The union corpus is what ``exp_frontier_crossover.py`` and
``tpu_session.py`` phase 2 consume when present.

Run on CPU: ``python benchmarks/merge_deep.py``.
"""

import glob
import json
import os
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path

KEEP = int(os.environ.get("MERGE_KEEP", "256"))


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.ops import (
        SPEC_9,
        serving_config,
        solve_batch,
    )

    sources = sorted(
        p
        for p in glob.glob(os.path.join(REPO, "benchmarks", "corpus_9x9_deep*.npz"))
        if "union" not in os.path.basename(p)
    )
    boards = []
    seen = set()
    per_source = {}
    for p in sources:
        arr = np.load(p)["boards"].astype(np.int32)
        fresh = 0
        for b in arr:
            key = b.tobytes()
            if key in seen:
                continue
            seen.add(key)
            boards.append(b)
            fresh += 1
        per_source[os.path.basename(p)] = {"boards": len(arr), "fresh": fresh}
    boards = np.stack(boards)

    # the probe's exact view EXCEPT the iteration budget: the deepest mined
    # boards exceed serving's 4096-iteration first stage (that is what makes
    # them deep — serving finishes them via the engine's deep retry), so
    # scoring here runs the full budget to get true per-board sweep counts
    # and to assert every kept board actually solves
    cfg = dict(serving_config(9), waves=1, max_iters=65536)
    solve = jax.jit(lambda g: solve_batch(g, SPEC_9, **cfg))
    M = len(boards)
    P2 = 1 << max(0, M - 1).bit_length()
    padded = (
        np.concatenate([boards, np.zeros((P2 - M, 9, 9), np.int32)])
        if P2 > M
        else boards
    )
    res = jax.block_until_ready(solve(jnp.asarray(padded)))
    sweeps = np.asarray(res.validations)[:M]
    guesses = np.asarray(res.guesses)[:M]
    assert bool(np.asarray(res.solved)[:M].all()), "deep corpora must solve"

    order = np.argsort(-sweeps)[:KEEP]
    out = os.path.join(REPO, "benchmarks", "corpus_9x9_deep_union.npz")
    np.savez_compressed(
        out,
        boards=boards[order],
        sweeps=sweeps[order],
        guesses=guesses[order],
    )
    record = {
        "sources": per_source,
        "union_unique": M,
        "kept": len(order),
        "sweeps_max": int(sweeps[order][0]),
        "sweeps_min_kept": int(sweeps[order][-1]),
        "guesses_max": int(guesses[order].max()),
        "corpus": os.path.basename(out),
        "t": round(time.time(), 1),
    }
    with open(
        os.path.join(REPO, "benchmarks", "merge_deep_r4.json"), "a"
    ) as f:
        f.write(json.dumps(record) + "\n")
    print(json.dumps(record, indent=1))


if __name__ == "__main__":
    main()
