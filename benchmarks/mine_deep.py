"""Hill-climb for deep-search 9×9 boards (VERDICT r3 task 3, stage 2).

Random certified-unique minimal puzzles top out at ~50 bucket-path guesses
(benchmarks/make_adversarial.py — the serving config's propagation floor is
that strong), which never lets the frontier race win. This miner searches
the puzzle space *adversarially*: a beam of elite puzzles is mutated
(clue swaps/removals that provably preserve having-a-solution, with a
budgeted uniqueness certificate per mutant), every candidate generation is
scored by the XLA solver's per-board guess count under the exact bucket-1
serving configuration (waves=1 — what the auto-route probe sees), and the
deepest survivors breed the next round.

Emits ``corpus_9x9_deep_{K}.npz`` (boards + guesses) for
benchmarks/exp_frontier_crossover.py and the routing-policy tests.

Run on CPU (no TPU claim): ``python benchmarks/mine_deep.py``.
"""

import json
import os
import random
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path

SECONDS = float(os.environ.get("MINE_SECONDS", "1800"))
RESTART_S = float(os.environ.get("MINE_RESTART_S", "300"))
KEEP = int(os.environ.get("MINE_KEEP", "128"))
BEAM = 48          # elites mutated each round
MUTANTS = 12       # children per elite per round
POOL = 384         # elite pool size between rounds
SEED = int(os.environ.get("MINE_SEED", "20260731"))
# Output filename tag: a second independent mining run (VERDICT r3 task 5)
# must not overwrite the first run's corpus — distinct tags, then
# benchmarks/merge_deep.py unions them for the crossover experiment.
TAG = os.environ.get("MINE_TAG", "")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch
    from sudoku_solver_distributed_tpu.models.generator import _count, _solve
    from sudoku_solver_distributed_tpu.ops import (
        SPEC_9,
        serving_config,
        solve_batch,
    )

    rng = random.Random(SEED)
    cfg = dict(serving_config(9), waves=1)  # the bucket-1/probe view
    solve = jax.jit(lambda g: solve_batch(g, SPEC_9, **cfg))

    def score(boards: np.ndarray) -> np.ndarray:
        """Per-board guesses; batches are pow2-padded with empty boards so
        the jit shape set stays tiny."""
        M = len(boards)
        P2 = 1 << max(0, M - 1).bit_length()
        if P2 > M:
            boards = np.concatenate(
                [boards, np.zeros((P2 - M, 9, 9), np.int32)]
            )
        res = jax.block_until_ready(solve(jnp.asarray(boards)))
        return np.asarray(res.guesses)[:M]

    def mutants(board: np.ndarray, solution: np.ndarray, n: int):
        """Children that provably keep ``solution`` as a solution:
        removing clues only relaxes; added clues come from ``solution``.
        Jump sizes up to 3 clues keep the walk from freezing once the
        1-2-clue neighborhood of an elite is exhausted (the round-3 first
        run plateaued at 567 guesses within 2 minutes that way)."""
        out = []
        filled = np.argwhere(board > 0)
        holes = np.argwhere(board == 0)
        for _ in range(n):
            child = board.copy()
            op = rng.random()
            k = rng.choice((1, 1, 2, 2, 3))
            if op < 0.45 and len(filled) > 17 + k:      # remove k clues
                for idx in rng.sample(range(len(filled)), k):
                    i, j = filled[idx]
                    child[i, j] = 0
            elif op < 0.9 and len(holes) and len(filled) > 17:  # swap
                for _ in range(rng.choice((1, 1, 2))):
                    hi = np.argwhere(child == 0)
                    i, j = hi[rng.randrange(len(hi))]
                    child[i, j] = solution[i, j]
                for idx in rng.sample(range(len(filled)), min(k, len(filled))):
                    fi, fj = filled[idx]
                    child[fi, fj] = 0
            else:                                       # add a clue
                if not len(holes):
                    continue
                i, j = holes[rng.randrange(len(holes))]
                child[i, j] = solution[i, j]
            out.append(child)
        return out

    def seed_pool(restart: int):
        """Fresh starting pool per restart: the shallow adversarial harvest
        + restart-specific minimal puzzles (outcomes are trajectory-
        dominated — observed 567/272/250 across runs — so the miner is a
        PORTFOLIO of short greedy climbs merged at the end)."""
        seeds = []
        adv = os.path.join(
            REPO, "benchmarks", "corpus_9x9_adversarial_128.npz"
        )
        if os.path.exists(adv):
            seeds.append(np.load(adv)["boards"])
        seeds.append(
            generate_batch(128, 64, seed=SEED + 7919 * restart, unique=True)
        )
        boards = np.concatenate(seeds).astype(np.int32)
        sols = np.stack(
            [np.asarray(_solve(b.tolist()), np.int32) for b in boards]
        )
        return list(zip(boards, sols, score(boards)))

    best: list = []  # global elite across restarts

    def save(tag=""):
        merged = sorted(best + pool, key=lambda t: -t[2])[:KEEP]
        name = f"corpus_9x9_deep_{TAG}_{KEEP}" if TAG else f"corpus_9x9_deep_{KEEP}"
        out = os.path.join(REPO, "benchmarks", f"{name}.npz")
        np.savez_compressed(
            out,
            boards=np.stack([t[0] for t in merged]),
            guesses=np.asarray([int(t[2]) for t in merged]),
        )
        return out

    t_global = time.time()
    restart = 0
    rounds = 0
    pool = seed_pool(restart)
    pool.sort(key=lambda t: -t[2])
    seen = {t[0].tobytes() for t in pool}
    t0 = time.time()
    stale = 0
    while time.time() - t_global < SECONDS:
        if time.time() - t0 > RESTART_S:
            # bank this climb and start a fresh trajectory
            best = sorted(best + pool, key=lambda t: -t[2])[:POOL]
            restart += 1
            rng.seed(SEED + 104729 * restart)
            pool = seed_pool(restart)
            pool.sort(key=lambda t: -t[2])
            seen = {t[0].tobytes() for t in pool}
            t0 = time.time()
            print(
                f"# restart {restart}: banked best {int(best[0][2])}",
                flush=True,
            )
        rounds += 1
        # exploration set: the apex + a weighted-random slice of the pool
        # (pure top-BEAM converges and freezes); plus fresh minimal puzzles
        # each round so the walk never runs out of new basins
        elites = pool[:BEAM]  # pure greedy: fastest climber on this landscape
        fresh = generate_batch(
            8, 64, seed=SEED + 1000 * (restart + 1) + rounds, unique=True
        )
        fresh_sols = [
            np.asarray(_solve(b.tolist()), np.int32) for b in fresh
        ]
        cand_b, cand_s = list(fresh.astype(np.int32)), list(fresh_sols)
        cand_b = [b for b in cand_b if b.tobytes() not in seen]
        cand_s = cand_s[: len(cand_b)]
        for b in cand_b:
            seen.add(b.tobytes())
        for board, solution, _ in elites:
            for child in mutants(board, solution, MUTANTS):
                key = child.tobytes()
                if key in seen:
                    continue
                seen.add(key)
                # budgeted uniqueness certificate; inconclusive → reject
                if _count(child.tolist(), limit=2) != 1:
                    continue
                cand_b.append(child)
                cand_s.append(solution)
        if not cand_b:
            stale += 1
            continue
        stale = 0
        cand_b = np.stack(cand_b)
        cand_g = score(cand_b)
        pool.extend(zip(cand_b, cand_s, cand_g))
        pool.sort(key=lambda t: -t[2])
        del pool[POOL:]
        if rounds % 50 == 0:
            save()  # periodic snapshot: a kill loses ≤50 rounds
        if rounds % 10 == 0:
            top = [int(t[2]) for t in pool[:8]]
            print(
                f"# round {rounds}: top guesses {top} "
                f"({time.time() - t0:.0f}s, pool p50 "
                f"{int(pool[len(pool) // 2][2])})",
                flush=True,
            )

    out = save()
    top = sorted(best + pool, key=lambda t: -t[2])[:KEEP]
    print(
        json.dumps(
            {
                "rounds": rounds,
                "restarts": restart + 1,
                "kept": len(top),
                "guesses_max": int(top[0][2]),
                "guesses_min_kept": int(top[-1][2]),
                "clues_min": int(min((t[0] > 0).sum() for t in top)),
                "corpus": os.path.basename(out),
                "elapsed_s": round(time.time() - t_global, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
