"""Second-method deep-board miner: simulated annealing on the sweep count.

VERDICT r3 task 5: the routing boundary (frontier_escalate_iters=512) rested
on ONE hill-climb run's adversarial distribution (benchmarks/mine_deep.py,
MINE_SEED=20260731). This miner is deliberately different on every axis that
could bias that distribution:

* **method** — per-chain simulated annealing (downhill moves accepted with
  exp(Δ/T), geometric cooling, per-chain reheats), not a greedy elite beam;
* **scorer** — per-board analysis-sweep count (``SolveResult.validations``,
  ≈ the board's lockstep iterations — the unit the auto-route probe
  observes), not the guess count;
* **seeds** — fresh certified-unique minimal puzzles only (no shared
  adversarial harvest), under an independent MINE_SEED.

Mutations must preserve having-a-solution (clue removals only relax; added
clues come from the chain's reference solution) and every accepted state
carries a budgeted uniqueness certificate, like the first miner — those are
correctness constraints, not search-strategy choices.

Emits ``corpus_{N}x{N}_deep_anneal_{K}.npz`` (boards + guesses + sweeps);
``MINE_SIZE`` selects the board size (9 default; 16 mines the hexadoku
deep corpus for the size-specific crossover table, ROADMAP gap #6).
``benchmarks/merge_deep.py`` unions the two miners' corpora for the
crossover experiment.

Run on CPU (no TPU claim): ``python benchmarks/mine_deep_anneal.py``.
"""

import json
import os
import random
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path

SECONDS = float(os.environ.get("MINE_SECONDS", "1800"))
SIZE = int(os.environ.get("MINE_SIZE", "9"))
_HOLES = {9: 64, 16: 140, 25: 320}
KEEP = int(os.environ.get("MINE_KEEP", "128"))
CHAINS = 64            # independent annealing walkers, scored as one batch
SEED = int(os.environ.get("MINE_SEED", "90210"))
T0 = float(os.environ.get("MINE_T0", "40.0"))    # initial temperature (sweeps)
COOL = float(os.environ.get("MINE_COOL", "0.995"))  # per-round geometric cooling
REHEAT_ROUNDS = int(os.environ.get("MINE_REHEAT", "150"))  # stagnation reset


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.models import generate_batch
    from sudoku_solver_distributed_tpu.models.generator import _count, _solve
    from sudoku_solver_distributed_tpu.ops import (
        serving_config,
        solve_batch,
        spec_for_size,
    )

    rng = random.Random(SEED)
    spec = spec_for_size(SIZE)
    cfg = dict(serving_config(SIZE), waves=1)  # the bucket-1/probe view
    # MINE_MAX_ITERS caps the scorer's budget below the serving cap: at
    # 25x25 (serving cap 65536) an uncapped scorer would spend minutes per
    # round once a chain finds a deep board — the scorer saturating at the
    # cap just means "at least this deep", which is all the ranking needs
    if os.environ.get("MINE_MAX_ITERS"):
        cfg["max_iters"] = int(os.environ["MINE_MAX_ITERS"])
    solve = jax.jit(lambda g: solve_batch(g, spec, **cfg))
    # minimal-clue safety floor for mutations (9x9: the classic 17)
    clue_floor = spec.cells // 5 + 1

    def score(boards: np.ndarray):
        """Per-board (sweeps, guesses); pow2-padded like the first miner."""
        M = len(boards)
        P2 = 1 << max(0, M - 1).bit_length()
        if P2 > M:
            boards = np.concatenate(
                [boards, np.zeros((P2 - M, SIZE, SIZE), np.int32)]
            )
        res = jax.block_until_ready(solve(jnp.asarray(boards)))
        return (
            np.asarray(res.validations)[:M],
            np.asarray(res.guesses)[:M],
        )

    def _key(sweeps, guesses) -> float:
        """Annealing objective. Guesses tie-break (scaled below any 1-sweep
        delta): with MINE_MAX_ITERS the sweep score saturates at the cap,
        and without a tie-break two at-cap boards score delta=0 — moves
        between them are always accepted and a chain can random-walk from
        a very deep board to a barely-at-cap one with no restoring signal
        (code-review r4). Guesses keep climbing past the cap, so they
        restore the gradient and order the at-cap corpus rows."""
        return float(sweeps) + float(guesses) / 10000.0

    def propose(board: np.ndarray, solution: np.ndarray) -> np.ndarray:
        """One mutation preserving `solution` as a solution."""
        child = board.copy()
        filled = np.argwhere(child > 0)
        holes = np.argwhere(child == 0)
        op = rng.random()
        k = rng.choice((1, 1, 1, 2, 2, 3))
        if op < 0.5 and len(filled) > clue_floor + k:         # remove k clues
            for idx in rng.sample(range(len(filled)), k):
                i, j = filled[idx]
                child[i, j] = 0
        elif op < 0.95 and len(holes) and len(filled) > clue_floor:  # swap
            i, j = holes[rng.randrange(len(holes))]
            child[i, j] = solution[i, j]
            filled2 = np.argwhere(child > 0)
            for idx in rng.sample(range(len(filled2)), min(k, len(filled2))):
                fi, fj = filled2[idx]
                child[fi, fj] = 0
        elif len(holes):                              # add a clue
            i, j = holes[rng.randrange(len(holes))]
            child[i, j] = solution[i, j]
        return child

    def fresh_chains(n, tag):
        boards = generate_batch(
            n, _HOLES[SIZE], size=SIZE, seed=SEED + 7717 * tag, unique=True
        ).astype(np.int32)
        sols = np.stack(
            [np.asarray(_solve(b.tolist()), np.int32) for b in boards]
        )
        sweeps, guesses = score(boards)
        return list(boards), list(sols), list(sweeps), list(guesses)

    t_start = time.time()
    cur_b, cur_s, cur_sw, cur_g = fresh_chains(CHAINS, 0)
    best: dict = {}  # board-bytes -> (board, sweeps, guesses)
    stagnant = [0] * CHAINS
    T = [T0] * CHAINS
    rounds = 0
    reheats = 0

    def bank(i):
        key = cur_b[i].tobytes()
        if key not in best or _key(best[key][1], best[key][2]) < _key(
            cur_sw[i], cur_g[i]
        ):
            best[key] = (cur_b[i].copy(), int(cur_sw[i]), int(cur_g[i]))

    for i in range(CHAINS):
        bank(i)

    def save():
        top = sorted(best.values(), key=lambda t: -_key(t[1], t[2]))[:KEEP]
        out = os.path.join(
            REPO, "benchmarks", f"corpus_{SIZE}x{SIZE}_deep_anneal_{KEEP}.npz"
        )
        np.savez_compressed(
            out,
            boards=np.stack([t[0] for t in top]),
            sweeps=np.asarray([t[1] for t in top]),
            guesses=np.asarray([t[2] for t in top]),
        )
        return out, top

    while time.time() - t_start < SECONDS:
        rounds += 1
        proposals = []
        valid = []
        for i in range(CHAINS):
            child = propose(cur_b[i], cur_s[i])
            # budgeted uniqueness certificate; inconclusive → keep current
            if _count(child.tolist(), limit=2) == 1:
                proposals.append(child)
                valid.append(i)
            T[i] = max(T[i] * COOL, 0.5)
        if not proposals:
            continue
        prop_sw, prop_g = score(np.stack(proposals))
        for j, i in enumerate(valid):
            delta = _key(prop_sw[j], prop_g[j]) - _key(cur_sw[i], cur_g[i])
            if delta >= 0 or rng.random() < np.exp(delta / T[i]):
                cur_b[i] = proposals[j]
                cur_sw[i] = prop_sw[j]
                cur_g[i] = prop_g[j]
                if delta > 0:
                    stagnant[i] = 0
                    bank(i)
                else:
                    stagnant[i] += 1
            else:
                stagnant[i] += 1
            if stagnant[i] >= REHEAT_ROUNDS:
                # reheat: fresh board + full temperature — an independent
                # chain restart, the annealing analog of the first miner's
                # portfolio restarts
                nb, ns, nsw, ng = fresh_chains(1, rounds * CHAINS + i)
                cur_b[i], cur_s[i] = nb[0], ns[0]
                cur_sw[i], cur_g[i] = nsw[0], ng[0]
                T[i] = T0
                stagnant[i] = 0
                reheats += 1
        if rounds % 50 == 0:
            save()
            top_sw = sorted((t[1] for t in best.values()), reverse=True)[:8]
            print(
                f"# round {rounds}: top sweeps {top_sw} "
                f"(T p50 {sorted(T)[CHAINS // 2]:.1f}, reheats {reheats}, "
                f"{time.time() - t_start:.0f}s)",
                flush=True,
            )

    out, top = save()
    print(
        json.dumps(
            {
                "method": "simulated_annealing",
                "size": SIZE,
                "scorer": "sweeps(validations)",
                "rounds": rounds,
                "reheats": reheats,
                "kept": len(top),
                "sweeps_max": int(top[0][1]),
                "sweeps_min_kept": int(top[-1][1]),
                "guesses_max": int(max(t[2] for t in top)),
                "corpus": os.path.basename(out),
                "elapsed_s": round(time.time() - t_start, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
