"""One-process TPU measurement session (round 4).

The repo's only TPU is a single pooled v5e behind a tunnel that grants one
claim at a time, and killing a mid-compile client wedges the claim pool-side
(docs/OPERATIONS.md).  So ALL on-chip questions for a session run from this
ONE process, patiently, in priority order, appending a JSON line per
completed measurement to ``benchmarks/tpu_session_r4.jsonl`` so partial
progress survives anything that happens later in the session.

Round-4 priority order (VERDICT.md round 3 "Next round" tasks 1-4, 6):

  1. 9x9 headline throughput with the EXACT serving config
     (``ops.serving_config(9)`` — the single definition site bench.py and
     the engine share), the driver-verifiable number the record lacks.
  2. Frontier crossover on-chip (deep corpus, 1-chip mesh) including the
     probe->race handoff comparison (VERDICT task 6) — the data that
     confirms or moves ``frontier_escalate_iters=512`` on TPU.
  3. Per-size throughput sweeps: 16x16 and 25x25 waves/pairs splits —
     the measurements ``ops/config.SERVING_CONFIG`` carries placeholders
     for (VERDICT weak #2).
  4. Serving-config splits on 9x9 (naked_pairs, waves 2/4, light_waves).
  5. Device-side latency: blocking and async-amortized 1-board solves
     (VERDICT task 4's device component).
  6. Pallas kernel compile attempt — LAST, because a failed/hung Mosaic
     compile must not cost the numbers above (VERDICT task 3: numbers or
     a dated reproduction of the error).

Stop discipline: the session checks ``benchmarks/tpu_stop`` (flag file)
and ``STOP_AT`` (absolute epoch) between phases and exits cleanly — the
claim MUST be free well before the driver's own end-of-round bench run.
On completion (or stop) a ``done`` marker is also appended to the round-3
jsonl so the still-running round-3 retry loop (which greps that file)
terminates itself.

Run with NO timeout wrapper:  nohup bash benchmarks/tpu_session_retry_r4.sh &
(A process-level flock makes concurrent wrappers harmless: one TPU client
at a time, the loser skips its attempt.)
"""

import json
import os
import sys
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path
OUT = os.path.join(REPO, "benchmarks", "tpu_session_r4.jsonl")
R3_OUT = os.path.join(REPO, "benchmarks", "tpu_session_r3.jsonl")
STOP_FLAG = os.path.join(REPO, "benchmarks", "tpu_stop")
# 2026-07-31 00:10 UTC — ~3h before the round-4 driver window closes; the
# claim must be free for the driver's bench.py run (VERDICT r3 weak #1).
STOP_AT = float(os.environ.get("TPU_SESSION_STOP_AT", "1785456600"))


def emit(record, path=OUT):
    record["t"] = round(time.time(), 1)
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
    print("EMIT", json.dumps(record), flush=True)


def finish(reason):
    """Mark both session files done so every retry loop generation exits."""
    emit({"phase": "done", "reason": reason})
    emit({"phase": "done", "reason": reason}, path=R3_OUT)


def should_stop():
    return os.path.exists(STOP_FLAG) or time.time() > STOP_AT


def time_solve(solve, dev_boards, batch, repeats=5):
    """bench.py methodology: sustained (async back-to-back) + blocking best."""
    import jax

    t0 = time.perf_counter()
    outs = [solve(dev_boards) for _ in range(repeats)]
    jax.block_until_ready(outs[-1])
    sustained = (time.perf_counter() - t0) / repeats
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = jax.block_until_ready(solve(dev_boards))
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {
        "pps": round(batch / min(best, sustained), 1),
        "sustained_ms": round(sustained * 1000, 2),
        "blocking_best_ms": round(best * 1000, 2),
        "iters": int(res.iters),
    }


def main():
    # One session process at a time, enforced (not just documented): the
    # round-3 wrapper may still be looping over this same file, and a second
    # wrapper launched per the docstring must not race it for the one-claim
    # pooled chip (docs/OPERATIONS.md). The flock lives for the process.
    import fcntl

    lock = open(os.path.join(REPO, "benchmarks", ".tpu_session.lock"), "w")
    try:
        fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        print(
            "another tpu_session.py holds the claim lock — skipping this "
            "attempt (one TPU client at a time)",
            flush=True,
        )
        return
    if should_stop():
        finish("stop flag/deadline before start")
        return
    emit({"phase": "start", "pid": os.getpid(), "round": 4})

    import jax

    t0 = time.perf_counter()
    devs = jax.devices()
    emit(
        {
            "phase": "backend_up",
            "init_s": round(time.perf_counter() - t0, 1),
            "devices": [str(d) for d in devs],
        }
    )

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.ops import (
        serving_config,
        solve_batch,
        spec_for_size,
    )

    def load_corpus(size):
        import glob

        paths = glob.glob(
            os.path.join(REPO, "benchmarks", f"corpus_{size}x{size}_hard_*.npz")
        )
        best_path = max(
            paths, key=lambda p: int(p.rsplit("_", 1)[1].split(".")[0])
        )
        return np.load(best_path)["boards"], os.path.basename(best_path)

    def run_config(size, boards, name, **kw):
        spec = spec_for_size(size)
        solve = jax.jit(lambda g: solve_batch(g, spec, **kw))
        dev = jnp.asarray(boards)
        t0 = time.perf_counter()
        res = jax.block_until_ready(solve(dev))
        compile_s = round(time.perf_counter() - t0, 1)
        solved = bool(np.asarray(res.solved).all())
        stats = time_solve(solve, dev, len(boards))
        emit(
            {
                "phase": "measure",
                "name": name,
                "size": size,
                "batch": len(boards),
                "compile_s": compile_s,
                "all_solved": solved,
                **stats,
            }
        )
        return stats

    # ---- phase 1: 9x9 headline — the EXACT bench.py/serving config --------
    b9, corpus9 = load_corpus(9)
    emit({"phase": "corpus", "size": 9, "file": corpus9, "n": len(b9)})
    cfg9 = serving_config(9)
    try:
        run_config(9, b9, "headline_9x9_serving_config", **cfg9)
    except Exception as e:  # noqa: BLE001 — record, let the wrapper retry
        emit({"phase": "error", "name": "headline", "err": repr(e)[:500]})
        # NO done marker here: a transient compile-time UNAVAILABLE (the
        # round-3 failure mode) must leave the patient retry wrapper alive
        # to try again when the claim frees; the deadline check at start
        # writes the markers once the session window truly closes.
        raise

    # ---- phase 2 setup (shared with 2b; each phase fails independently) ---
    mesh = picks = None
    try:
        from sudoku_solver_distributed_tpu.engine import SolverEngine
        from sudoku_solver_distributed_tpu.parallel import (
            default_mesh,
            frontier_solve,
        )

        mesh = default_mesh()
        deep_path = os.path.join(
            REPO, "benchmarks", "corpus_9x9_deep_union.npz"
        )
        if not os.path.exists(deep_path):
            deep_path = os.path.join(
                REPO, "benchmarks", "corpus_9x9_deep_128.npz"
            )
        try:
            deep = np.load(deep_path)
            picks = list(deep["boards"][:16]) + list(b9[:4])
            xo_corpus = os.path.basename(deep_path)
        except Exception as e:  # noqa: BLE001 — deep corpus is optional
            emit(
                {
                    "phase": "error",
                    "name": "deep_corpus_load",
                    "err": repr(e)[:300],
                }
            )
            picks = list(b9[:8])
            xo_corpus = corpus9 + " (deep-corpus fallback)"
    except Exception as e:  # noqa: BLE001
        emit({"phase": "error", "name": "crossover_setup", "err": repr(e)[:600]})

    # ONE engine serves phases 2 and 2b (code-review r4): its warmup compiles
    # the bucket-1 program, the auto-route quick probe, and the racer rungs
    # exactly once inside the deadline-bounded claim window; the racer itself
    # is module-cached (frontier._make_racer_cached), shared with the direct
    # frontier_solve calls below.
    eng = None
    if picks is not None and not should_stop():
        try:
            eng = SolverEngine(
                buckets=(1,),
                frontier_mesh=mesh,
                frontier_states_per_device=64,
                # persistent plane (compilecache/): AOT artifacts baked in
                # an earlier claim window load instead of re-compiling —
                # on the flaky tunnel, compiles are the scarce resource
                compile_cache_dir=os.environ.get(
                    "TPU_COMPILE_PLANE_DIR",
                    os.path.join(REPO, "benchmarks", ".compile_plane"),
                ),
            )
            # budgeted: a claim window that cannot afford the full ladder
            # still flips tier-0 warm and runs the phases on warm widths
            eng.warmup(
                budget_s=float(os.environ.get("TPU_WARMUP_BUDGET_S", "240"))
            )
            emit({"phase": "engine_warm_info", **eng.warm_info()})
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "engine_warmup", "err": repr(e)[:600]})
            eng = None

    # ---- phase 2: frontier crossover on-chip (incl. probe handoff) --------
    if eng is not None and not should_stop():
        try:
            race_kw = dict(
                states_per_device=64,
                locked=eng.locked_candidates,
                waves=eng.waves,
                max_depth=eng.max_depth,
                naked_pairs=eng.naked_pairs,
            )
            rows = []
            for board in picks:
                t0 = time.perf_counter()
                sol, info = eng.solve_one(board, frontier=False)
                bucket_ms = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                rsol, _ = frontier_solve(board, mesh, **race_kw)
                race_ms = (time.perf_counter() - t0) * 1e3
                # verdicts must agree or race_ms is a meaningless fast
                # failure — the one-shot claim window can't be re-run, so
                # a corrupted row must be visible in the artifact
                rows.append(
                    {
                        "guesses": int(info["guesses"]),
                        "bucket_ms": round(bucket_ms, 1),
                        "race_ms": round(race_ms, 1),
                        "verdicts_agree": (sol is None) == (rsol is None),
                    }
                )
            emit(
                {
                    "phase": "frontier_crossover_1chip",
                    "corpus": xo_corpus,
                    "rows": rows,
                }
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "crossover", "err": repr(e)[:600]})

    # ---- phase 2b: auto-route e2e (probe+escalate) on the deep tail -------
    # What /solve actually pays under --frontier-route auto: the 512-iter
    # probe, then the race on escalation. Compares the double-pay VERDICT
    # weak #4 flags against the race-only and bucket-only numbers above.
    if eng is not None and not should_stop():
        try:
            auto_rows = []
            for board in picks[:8]:
                before = eng.frontier_escalations
                t0 = time.perf_counter()
                sol, info = eng.solve_one(board)
                auto_ms = (time.perf_counter() - t0) * 1e3
                auto_rows.append(
                    {
                        "auto_ms": round(auto_ms, 1),
                        "escalated": eng.frontier_escalations > before,
                        "solved": sol is not None,
                    }
                )
            emit({"phase": "auto_route_e2e", "rows": auto_rows})
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "auto_route", "err": repr(e)[:600]})

    # ---- phase 3: per-size throughput sweeps (16x16, 25x25) ---------------
    for size, depth, iters in ((16, (64, 256), 16384), (25, None, 65536)):
        if should_stop():
            break
        try:
            bs, cname = load_corpus(size)
            emit({"phase": "corpus", "size": size, "file": cname, "n": len(bs)})
            for waves in (1, 2, 3):
                run_config(
                    size, bs, f"{size}x{size}_waves{waves}",
                    max_iters=iters, max_depth=depth,
                    locked_candidates=True, waves=waves, naked_pairs=False,
                )
            run_config(
                size, bs, f"{size}x{size}_waves1_pairsON",
                max_iters=iters, max_depth=depth,
                locked_candidates=True, waves=1, naked_pairs=True,
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": f"size{size}", "err": repr(e)[:500]})

    # ---- phase 4: serving-config splits on 9x9 ---------------------------
    if not should_stop():
        splits = [
            ("9x9_pairsON", {**cfg9, "naked_pairs": True}),
            ("9x9_waves2", {**cfg9, "waves": 2}),
            ("9x9_waves4", {**cfg9, "waves": 4}),
            ("9x9_light_waves4", {**cfg9, "waves": 4, "light_waves": True}),
        ]
        for name, kw in splits:
            try:
                run_config(9, b9, name, **kw)
            except Exception as e:  # noqa: BLE001
                emit({"phase": "error", "name": name, "err": repr(e)[:500]})

    # ---- phase 5: single-board latency (blocking + amortized) -------------
    if not should_stop():
        try:
            spec = spec_for_size(9)
            # waves=1: the engine's real 1-board serving path compiles
            # waves_eff = 1 if B == 1 (engine.py _run — nothing to amortize
            # on a single board), so the latency artifact must measure that
            # configuration, not the batch config (code-review r4).
            solve1 = jax.jit(
                lambda g: solve_batch(g, spec, **{**cfg9, "waves": 1})
            )
            one = jnp.asarray(b9[:1])
            jax.block_until_ready(solve1(one))  # compile
            lat = []
            for i in range(40):
                one = jnp.asarray(b9[i : i + 1])
                t0 = time.perf_counter()
                jax.block_until_ready(solve1(one))
                lat.append((time.perf_counter() - t0) * 1e3)
            lat = np.asarray(lat)
            emit(
                {
                    "phase": "device_latency_1board",
                    "p50_ms": round(float(np.percentile(lat, 50)), 2),
                    "p95_ms": round(float(np.percentile(lat, 95)), 2),
                    "min_ms": round(float(lat.min()), 2),
                    "note": "blocking 1-board solve incl. tunnel RTT per call",
                }
            )
            n_async = 64
            t0 = time.perf_counter()
            outs = [solve1(jnp.asarray(b9[i : i + 1])) for i in range(n_async)]
            jax.block_until_ready(outs[-1])
            per = (time.perf_counter() - t0) / n_async * 1e3
            emit(
                {
                    "phase": "device_latency_1board_amortized",
                    "per_request_ms": round(per, 3),
                    "n": n_async,
                    "note": "async back-to-back 1-board solves, one sync: "
                    "tunnel RTT amortized out — the co-located-serving bound",
                }
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "latency1", "err": repr(e)[:500]})

    # ---- phase 6: pallas compile attempt (LAST; may hang or crash) --------
    if not should_stop():
        try:
            emit({"phase": "pallas_attempt_start"})
            from sudoku_solver_distributed_tpu.ops.pallas_solver import (
                solve_batch_pallas,
            )

            spec = spec_for_size(9)
            small = jnp.asarray(b9[:256])
            t0 = time.perf_counter()
            res = jax.block_until_ready(
                solve_batch_pallas(small, spec, max_depth=(32, 81))
            )
            compile_s = round(time.perf_counter() - t0, 1)
            ok = bool(np.asarray(res.solved).all())
            solve_p = jax.jit(
                lambda g: solve_batch_pallas(g, spec, max_depth=(32, 81))
            )
            jax.block_until_ready(solve_p(jnp.asarray(b9)))
            stats = time_solve(solve_p, jnp.asarray(b9), len(b9))
            emit(
                {
                    "phase": "pallas_result",
                    "compile_s": compile_s,
                    "all_solved_256": ok,
                    **stats,
                }
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "pallas_error", "err": repr(e)[:800]})

    finish("session complete" if not should_stop() else "stopped at deadline")


if __name__ == "__main__":
    main()
