"""One-process TPU measurement session (round 3).

The repo's only TPU is a single pooled v5e behind a tunnel that grants one
claim at a time, and killing a mid-compile client wedges the claim pool-side
(docs/OPERATIONS.md).  So ALL on-chip questions for a session run from this
ONE process, patiently, in priority order, appending a JSON line per
completed measurement to ``benchmarks/tpu_session_r3.jsonl`` so partial
progress survives anything that happens later in the session:

  1. 9x9 headline throughput (the bench config) — the driver-verifiable
     number that VERDICT.md round 2 flagged as the record gap.
  2. Serving-config splits: naked_pairs on/off, light_waves — resolves the
     bench/serving divergence (VERDICT weak #1) by measurement.
  3. Per-size throughput: 16x16 and 25x25 (largest committed corpus found),
     including a small waves sweep (their round-2 numbers were waves=1).
  4. Single-board blocking solve time (device-side latency component).
  5. Pallas kernel compile attempt — LAST, because a failed/hung Mosaic
     compile must not cost the numbers above (round-2 postmortem:
     ROADMAP.md "Known gaps" #1).

Run with NO timeout wrapper:  nohup python benchmarks/tpu_session.py &
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "benchmarks", "tpu_session_r3.jsonl")


def emit(record):
    record["t"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
    print("EMIT", json.dumps(record), flush=True)


def time_solve(solve, dev_boards, batch, repeats=5):
    """bench.py methodology: sustained (async back-to-back) + blocking best."""
    import jax

    t0 = time.perf_counter()
    outs = [solve(dev_boards) for _ in range(repeats)]
    jax.block_until_ready(outs[-1])
    sustained = (time.perf_counter() - t0) / repeats
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = jax.block_until_ready(solve(dev_boards))
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {
        "pps": round(batch / min(best, sustained), 1),
        "sustained_ms": round(sustained * 1000, 2),
        "blocking_best_ms": round(best * 1000, 2),
        "iters": int(res.iters),
    }


def main():
    emit({"phase": "start", "pid": os.getpid()})

    import jax

    t0 = time.perf_counter()
    devs = jax.devices()
    emit(
        {
            "phase": "backend_up",
            "init_s": round(time.perf_counter() - t0, 1),
            "devices": [str(d) for d in devs],
        }
    )

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.ops import solve_batch, spec_for_size

    def load_corpus(size):
        import glob

        paths = glob.glob(
            os.path.join(REPO, "benchmarks", f"corpus_{size}x{size}_hard_*.npz")
        )
        best_path = max(
            paths, key=lambda p: int(p.rsplit("_", 1)[1].split(".")[0])
        )
        return np.load(best_path)["boards"], os.path.basename(best_path)

    def run_config(size, boards, name, **kw):
        spec = spec_for_size(size)
        solve = jax.jit(lambda g: solve_batch(g, spec, **kw))
        dev = jnp.asarray(boards)
        t0 = time.perf_counter()
        res = jax.block_until_ready(solve(dev))
        compile_s = round(time.perf_counter() - t0, 1)
        solved = bool(np.asarray(res.solved).all())
        stats = time_solve(solve, dev, len(boards))
        emit(
            {
                "phase": "measure",
                "name": name,
                "size": size,
                "batch": len(boards),
                "compile_s": compile_s,
                "all_solved": solved,
                **stats,
            }
        )
        return stats

    # ---- phase 1: 9x9 headline (the exact bench.py config) ----------------
    b9, corpus9 = load_corpus(9)
    emit({"phase": "corpus", "size": 9, "file": corpus9, "n": len(b9)})
    base9 = dict(
        max_iters=4096, max_depth=(32, 81), locked_candidates=True, waves=3,
        naked_pairs=False,
    )
    try:
        run_config(9, b9, "headline_9x9_waves3_pairsoff", **base9)
    except Exception as e:  # noqa: BLE001 — record, keep the session alive
        emit({"phase": "error", "name": "headline", "err": repr(e)[:500]})
        raise  # headline failing means the backend is sick; stop cleanly

    # ---- phase 2: serving-config splits on 9x9 ---------------------------
    splits = [
        ("9x9_waves3_pairsON", {**base9, "naked_pairs": True}),
        ("9x9_light_waves4", {**base9, "waves": 4, "light_waves": True}),
        ("9x9_light_waves5", {**base9, "waves": 5, "light_waves": True}),
        ("9x9_waves2_pairsoff", {**base9, "waves": 2}),
        ("9x9_waves4_pairsoff", {**base9, "waves": 4}),
    ]
    for name, kw in splits:
        try:
            run_config(9, b9, name, **kw)
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": name, "err": repr(e)[:500]})

    # ---- phase 3: per-size throughput ------------------------------------
    for size, depth, iters in ((16, (64, 256), 16384), (25, None, 65536)):
        try:
            bs, cname = load_corpus(size)
            emit({"phase": "corpus", "size": size, "file": cname, "n": len(bs)})
            for waves in (1, 2, 3):
                run_config(
                    size, bs, f"{size}x{size}_waves{waves}",
                    max_iters=iters, max_depth=depth,
                    locked_candidates=True, waves=waves, naked_pairs=False,
                )
            run_config(
                size, bs, f"{size}x{size}_waves1_pairsON",
                max_iters=iters, max_depth=depth,
                locked_candidates=True, waves=1, naked_pairs=True,
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": f"size{size}", "err": repr(e)[:500]})

    # ---- phase 4: single-board blocking solve (device latency component) --
    try:
        spec = spec_for_size(9)
        solve1 = jax.jit(
            lambda g: solve_batch(
                g, spec, max_iters=4096, max_depth=(32, 81),
                locked_candidates=True, waves=1, naked_pairs=True,
            )
        )
        one = jnp.asarray(b9[:1])
        jax.block_until_ready(solve1(one))  # compile
        lat = []
        for i in range(40):
            one = jnp.asarray(b9[i : i + 1])
            t0 = time.perf_counter()
            jax.block_until_ready(solve1(one))
            lat.append((time.perf_counter() - t0) * 1e3)
        lat = np.asarray(lat)
        emit(
            {
                "phase": "device_latency_1board",
                "p50_ms": round(float(np.percentile(lat, 50)), 2),
                "p95_ms": round(float(np.percentile(lat, 95)), 2),
                "min_ms": round(float(lat.min()), 2),
                "note": "blocking 1-board solve incl. tunnel RTT per call",
            }
        )
    except Exception as e:  # noqa: BLE001
        emit({"phase": "error", "name": "latency1", "err": repr(e)[:500]})

    # ---- phase 4b: amortized 1-board device time ---------------------------
    # The blocking number above includes the tunnel RTT per call; dispatching
    # N solves back-to-back and syncing once bounds the device+serving cost a
    # CO-LOCATED client would see (the <5 ms north-star's real question).
    try:
        n_async = 64
        t0 = time.perf_counter()
        outs = [solve1(jnp.asarray(b9[i : i + 1])) for i in range(n_async)]
        jax.block_until_ready(outs[-1])
        per = (time.perf_counter() - t0) / n_async * 1e3
        emit(
            {
                "phase": "device_latency_1board_amortized",
                "per_request_ms": round(per, 3),
                "n": n_async,
                "note": "async back-to-back 1-board solves, one sync: "
                "tunnel RTT amortized out — the co-located-serving bound",
            }
        )
    except Exception as e:  # noqa: BLE001
        emit({"phase": "error", "name": "latency_amortized", "err": repr(e)[:500]})

    # ---- phase 4c: frontier crossover on-chip (deep corpus, 1-chip mesh) ---
    try:
        deep_path = os.path.join(
            REPO, "benchmarks", "corpus_9x9_deep_128.npz"
        )
        if os.path.exists(deep_path):
            from sudoku_solver_distributed_tpu.engine import SolverEngine
            from sudoku_solver_distributed_tpu.parallel import (
                default_mesh,
                frontier_solve,
            )

            deep = np.load(deep_path)
            picks = list(deep["boards"][:12]) + list(b9[:4])
            mesh = default_mesh()
            eng = SolverEngine(buckets=(1,))
            eng.warmup()
            race_kw = dict(
                states_per_device=64,
                locked=eng.locked_candidates,
                waves=eng.waves,
                max_depth=eng.max_depth,
                naked_pairs=eng.naked_pairs,
            )
            frontier_solve(picks[0], mesh, **race_kw)  # compile
            rows = []
            for board in picks:
                t0 = time.perf_counter()
                sol, info = eng.solve_one(board, frontier=False)
                bucket_ms = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                rsol, _ = frontier_solve(board, mesh, **race_kw)
                race_ms = (time.perf_counter() - t0) * 1e3
                # verdicts must agree or race_ms is a meaningless fast
                # failure — the one-shot claim window can't be re-run, so
                # a corrupted row must be visible in the artifact
                rows.append(
                    {
                        "guesses": int(info["guesses"]),
                        "bucket_ms": round(bucket_ms, 1),
                        "race_ms": round(race_ms, 1),
                        "verdicts_agree": (sol is None) == (rsol is None),
                    }
                )
            emit({"phase": "frontier_crossover_1chip", "rows": rows})
    except Exception as e:  # noqa: BLE001
        emit({"phase": "error", "name": "crossover", "err": repr(e)[:600]})

    # ---- phase 5: pallas compile attempt (LAST; may hang or crash) --------
    try:
        emit({"phase": "pallas_attempt_start"})
        from sudoku_solver_distributed_tpu.ops.pallas_solver import (
            solve_batch_pallas,
        )

        spec = spec_for_size(9)
        small = jnp.asarray(b9[:256])
        t0 = time.perf_counter()
        res = jax.block_until_ready(
            solve_batch_pallas(small, spec, max_depth=(32, 81))
        )
        compile_s = round(time.perf_counter() - t0, 1)
        ok = bool(np.asarray(res.solved).all())
        solve_p = jax.jit(
            lambda g: solve_batch_pallas(g, spec, max_depth=(32, 81))
        )
        jax.block_until_ready(solve_p(jnp.asarray(b9)))
        stats = time_solve(solve_p, jnp.asarray(b9), len(b9))
        emit(
            {
                "phase": "pallas_result",
                "compile_s": compile_s,
                "all_solved_256": ok,
                **stats,
            }
        )
    except Exception as e:  # noqa: BLE001
        emit({"phase": "pallas_error", "err": repr(e)[:800]})

    emit({"phase": "done"})


if __name__ == "__main__":
    main()
