"""One-process TPU measurement session (round 5) — probe-then-commit.

Rounds 2-4 made 13+ patient full-pipeline claim attempts and acquired the
pooled chip zero times; the only driver-verified TPU number on record is
round 1's 94,903.6 puzzles/s/chip (BENCH_r01.json). VERDICT r4 task 1
prescribes the restructure this file implements: assume the claim window,
when it opens, is SHORT, and make the first claim touch a minimal program
whose result is persisted the instant it exists.

Phase order (every phase appends a JSON line to tpu_session_r5.jsonl the
moment it completes; high-value phases ALSO write a standalone artifact
file immediately):

  1. MINIMAL headline: one warm ``solve_batch`` on the cached 4096-board
     corpus with the exact serving config (``ops.serving_config(9)`` —
     the single definition site bench.py and the engine share). The
     compile is the smallest that still measures the real serving
     program. Artifact: ``benchmarks/headline_tpu_r5.json``.
  1b. Full-batch headline on the 16384 corpus (round-1's batch; better
     amortization → the number to beat ≥100k/chip, BASELINE.md).
  2. Frontier crossover on-chip (deep union corpus, 1-chip mesh) +
     auto-route e2e — the data that confirms or moves
     ``frontier_escalate_iters=512`` on TPU (VERDICT r4 task 4).
     Artifact: ``benchmarks/xo_9_r5.json`` (platform-stamped).
  3. Per-size sweeps: 16x16 / 25x25 waves splits — the measurements
     ``ops/config.SERVING_CONFIG`` carries CPU-derived rows for.
  4. Serving-config splits on 9x9 (naked_pairs, waves 2/4, light).
  5. Device-side 1-board latency (blocking + async-amortized) — the
     TPU-side component of the <5 ms north star (VERDICT r4 task 5).
     Artifact: ``benchmarks/latency_tpu_r5.json``.
  6. Pallas Mosaic compile attempt — LAST: a failed/hung compile must
     not cost the numbers above (VERDICT r4 task 3: a timing or a
     dated reproduction of the error).

Init diagnostics (VERDICT r4 task 1c): a hang is distinguished from a
raise — the watchdog emits ``init_timeout`` with the waited duration
before exiting 3; a raised backend error emits ``init_error`` with the
full repr, so round 6 can tell a wedged pool from a broken tunnel.

Claim discipline (docs/OPERATIONS.md): one process, flock-enforced, no
external kill — the process dies only by its own watchdog or completion.
Run via ``nohup bash benchmarks/tpu_session_retry_r5.sh &``.
"""

import json
import os
import sys
import threading
import time

from _bootstrap import REPO  # noqa: E402 — repo root onto sys.path
OUT = os.path.join(REPO, "benchmarks", "tpu_session_r5.jsonl")
STOP_FLAG = os.path.join(REPO, "benchmarks", "tpu_stop")
# Default: ~9h after round-5 start (round began 2026-07-31 03:45 UTC,
# ~12h window) — the claim must be free well before the driver's own
# end-of-round bench.py run (the r4 lesson: VERDICT weak #1).
STOP_AT = float(os.environ.get("TPU_SESSION_STOP_AT", "1785502000"))
INIT_TIMEOUT_S = float(os.environ.get("TPU_INIT_TIMEOUT_S", "1500"))
# A single phase blocked past this is a wedged-tunnel compile RPC (the
# 2026-07-31 attempt-1 shape: backend_up in 0.1 s, then the first compile
# never returned), not a slow compile — healthy serving-config compiles
# measure minutes at most. The phase watchdog emits the diagnosis and
# exits 3 by its own hand so the wrapper can retry.
PHASE_TIMEOUT_S = float(os.environ.get("TPU_PHASE_TIMEOUT_S", "2400"))
TARGET_PER_CHIP = 100_000.0  # BASELINE.md 9x9 north star

# Persistent compile cache: a serving-config compile that succeeds ONCE is
# reused by every later attempt/phase and by bench.py (which owns the ONE
# path definition), so a short claim window is spent measuring, not
# compiling.
from _bootstrap import setup_compile_cache_env  # noqa: E402

setup_compile_cache_env()


def emit(record, path=OUT):
    record["t"] = round(time.time(), 1)
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
    print("EMIT", json.dumps(record), flush=True)


def write_artifact(name, payload):
    """Persist a standalone artifact file the moment the data exists."""
    path = os.path.join(REPO, "benchmarks", name)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    emit({"phase": "artifact", "file": name})


def should_stop():
    return os.path.exists(STOP_FLAG) or time.time() > STOP_AT


class PhaseWatchdog:
    """Re-armable deadline for device-blocking phases: if a phase blocks
    past its budget the process emits the diagnosis and exits 3 BY ITS OWN
    HAND (never an external kill — docs/OPERATIONS.md claim discipline),
    so the retry wrapper gets another attempt instead of waiting forever
    on a wedged compile RPC."""

    def __init__(self):
        self._label = None
        self._deadline = None
        self._lock = threading.Lock()
        t = threading.Thread(target=self._run, daemon=True)
        t.start()

    def arm(self, label: str, budget_s: float = PHASE_TIMEOUT_S):
        with self._lock:
            self._label = label
            self._deadline = time.time() + budget_s

    def disarm(self):
        with self._lock:
            self._label = None
            self._deadline = None

    def _run(self):
        while True:
            time.sleep(5)
            with self._lock:
                expired = (
                    self._deadline is not None and time.time() > self._deadline
                )
                label = self._label
            if expired:
                emit(
                    {
                        "phase": "phase_timeout",
                        "name": label,
                        "budget_s": PHASE_TIMEOUT_S,
                        "detail": "device call never returned — wedged "
                        "tunnel/compile RPC, not a slow compile",
                    }
                )
                os._exit(3)


def time_solve(solve, dev_boards, batch, repeats=5):
    """bench.py methodology: sustained (async back-to-back) + blocking best."""
    import jax

    t0 = time.perf_counter()
    outs = [solve(dev_boards) for _ in range(repeats)]
    jax.block_until_ready(outs[-1])
    sustained = (time.perf_counter() - t0) / repeats
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        res = jax.block_until_ready(solve(dev_boards))
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {
        "pps": round(batch / min(best, sustained), 1),
        "sustained_ms": round(sustained * 1000, 2),
        "blocking_best_ms": round(best * 1000, 2),
        "iters": int(res.iters),
    }


def main():
    import fcntl

    lock = open(os.path.join(REPO, "benchmarks", ".tpu_session.lock"), "w")
    try:
        fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        print(
            "another tpu_session holds the claim lock — skipping this "
            "attempt (one TPU client at a time)",
            flush=True,
        )
        return
    if should_stop():
        emit({"phase": "done", "reason": "stop flag/deadline before start"})
        return
    emit({"phase": "start", "pid": os.getpid(), "round": 5})

    # Init watchdog: distinguishes a HANG (pool-side claim held elsewhere —
    # emit init_timeout, exit 3 so the wrapper retries) from a RAISE
    # (sick terminal — caught below as init_error). The exit is by our own
    # hand, never an external kill (docs/OPERATIONS.md claim discipline).
    init_started = time.time()
    init_done = threading.Event()

    def _watchdog():
        if not init_done.wait(INIT_TIMEOUT_S):
            emit(
                {
                    "phase": "init_timeout",
                    "waited_s": round(time.time() - init_started, 1),
                    "detail": "jax.devices() never returned — pool-side "
                    "claim held elsewhere or tunnel wedged",
                }
            )
            os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()

    import jax

    t0 = time.perf_counter()
    try:
        devs = jax.devices()
    except Exception as e:  # noqa: BLE001 — the diagnostic IS the point
        emit(
            {
                "phase": "init_error",
                "after_s": round(time.perf_counter() - t0, 1),
                "err": repr(e)[:800],
            }
        )
        os._exit(3)
    init_done.set()
    platform = devs[0].platform
    emit(
        {
            "phase": "backend_up",
            "init_s": round(time.perf_counter() - t0, 1),
            "platform": platform,
            "devices": [str(d) for d in devs],
        }
    )

    import jax.numpy as jnp
    import numpy as np

    from sudoku_solver_distributed_tpu.ops import (
        serving_config,
        solve_batch,
        spec_for_size,
    )

    def corpus_path(size, batch):
        return os.path.join(
            REPO, "benchmarks", f"corpus_{size}x{size}_hard_{batch}.npz"
        )

    dog = PhaseWatchdog()

    def run_config(size, boards, name, repeats=5, **kw):
        spec = spec_for_size(size)
        solve = jax.jit(lambda g: solve_batch(g, spec, **kw))
        dev = jnp.asarray(boards)
        dog.arm(name)
        t0 = time.perf_counter()
        res = jax.block_until_ready(solve(dev))
        compile_s = round(time.perf_counter() - t0, 1)
        solved = bool(np.asarray(res.solved).all())
        stats = time_solve(solve, dev, len(boards), repeats=repeats)
        dog.disarm()
        emit(
            {
                "phase": "measure",
                "name": name,
                "size": size,
                "batch": len(boards),
                "compile_s": compile_s,
                "all_solved": solved,
                **stats,
            }
        )
        return stats, solved

    # ---- phase 1: MINIMAL headline — smallest real-serving-config touch ---
    cfg9 = serving_config(9)
    b4096 = np.load(corpus_path(9, 4096))["boards"]
    try:
        stats, solved = run_config(
            9, b4096, "headline_9x9_minimal_4096", repeats=3, **cfg9
        )
        write_artifact(
            "headline_tpu_r5.json",
            {
                "metric": "puzzles_per_sec_per_chip_hard9x9",
                "value": stats["pps"],
                "unit": "puzzles/s/chip",
                "vs_baseline": round(stats["pps"] / TARGET_PER_CHIP, 4),
                "platform": platform,
                "batch": 4096,
                "all_solved": solved,
                "config": cfg9,
                "note": "probe-then-commit phase-1 capture; driver artifact "
                "is BENCH_r05.json (end-of-round bench.py run)",
            },
        )
    except Exception as e:  # noqa: BLE001 — record, let the wrapper retry
        emit({"phase": "error", "name": "headline_minimal", "err": repr(e)[:600]})
        raise

    # ---- phase 1b: full-batch headline (round-1 batch, best amortization) -
    if not should_stop():
        try:
            b9 = np.load(corpus_path(9, 16384))["boards"]
            stats, solved = run_config(
                9, b9, "headline_9x9_serving_config_16384", **cfg9
            )
            write_artifact(
                "headline_tpu_r5_16384.json",
                {
                    "metric": "puzzles_per_sec_per_chip_hard9x9",
                    "value": stats["pps"],
                    "unit": "puzzles/s/chip",
                    "vs_baseline": round(stats["pps"] / TARGET_PER_CHIP, 4),
                    "platform": platform,
                    "batch": 16384,
                    "all_solved": solved,
                    "config": cfg9,
                },
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "headline_16384", "err": repr(e)[:600]})
            b9 = b4096
    else:
        b9 = b4096

    # ---- phase 2: frontier crossover on-chip + auto-route e2e -------------
    eng = mesh = picks = None
    if not should_stop():
        try:
            from sudoku_solver_distributed_tpu.engine import SolverEngine
            from sudoku_solver_distributed_tpu.parallel import (
                default_mesh,
                frontier_solve,
            )

            mesh = default_mesh()
            deep_path = os.path.join(
                REPO, "benchmarks", "corpus_9x9_deep_union.npz"
            )
            deep = np.load(deep_path)
            picks = list(deep["boards"][:16]) + list(b9[:4])
            eng = SolverEngine(
                buckets=(1,),
                frontier_mesh=mesh,
                frontier_states_per_device=64,
                # persistent plane (compilecache/): artifacts baked in an
                # earlier window load instead of re-compiling; the XLA
                # layer keeps riding COMPILE_CACHE_DIR (first-wins)
                compile_cache_dir=os.environ.get(
                    "TPU_COMPILE_PLANE_DIR",
                    os.path.join(REPO, "benchmarks", ".compile_plane"),
                ),
            )
            dog.arm("engine_warmup")
            # budgeted tiered warmup (ISSUE 4): tier 0 always compiles;
            # a short window skips the wide rungs instead of dying in them
            eng.warmup(
                budget_s=float(os.environ.get("TPU_WARMUP_BUDGET_S", "240"))
            )
            dog.disarm()
            emit({"phase": "engine_warm_info", **eng.warm_info()})
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "crossover_setup", "err": repr(e)[:600]})
            eng = None

    if eng is not None and not should_stop():
        try:
            race_kw = dict(
                states_per_device=64,
                locked=eng.locked_candidates,
                waves=eng.waves,
                max_depth=eng.max_depth,
                naked_pairs=eng.naked_pairs,
            )
            rows = []
            dog.arm("crossover")
            for board in picks:
                t0 = time.perf_counter()
                sol, info = eng.solve_one(board, frontier=False)
                bucket_ms = (time.perf_counter() - t0) * 1e3
                t0 = time.perf_counter()
                rsol, _ = frontier_solve(board, mesh, **race_kw)
                race_ms = (time.perf_counter() - t0) * 1e3
                rows.append(
                    {
                        "guesses": int(info["guesses"]),
                        "iters": int(info.get("iters", -1)),
                        "bucket_ms": round(bucket_ms, 1),
                        "race_ms": round(race_ms, 1),
                        "verdicts_agree": (sol is None) == (rsol is None),
                    }
                )
            dog.disarm()
            emit({"phase": "frontier_crossover_1chip", "rows": rows})
            write_artifact(
                "xo_9_r5.json",
                {
                    "platform": platform,
                    "mesh_devices": int(np.prod(list(mesh.shape.values()))),
                    "states_per_device": 64,
                    "boards": "corpus_9x9_deep_union.npz[:16] + hard[:4]",
                    "rows": rows,
                },
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "crossover", "err": repr(e)[:600]})

    if eng is not None and not should_stop():
        try:
            auto_rows = []
            dog.arm("auto_route")
            for board in picks[:8]:
                before = eng.frontier_escalations
                t0 = time.perf_counter()
                sol, info = eng.solve_one(board)
                auto_ms = (time.perf_counter() - t0) * 1e3
                auto_rows.append(
                    {
                        "auto_ms": round(auto_ms, 1),
                        "escalated": eng.frontier_escalations > before,
                        "solved": sol is not None,
                    }
                )
            dog.disarm()
            emit({"phase": "auto_route_e2e", "rows": auto_rows})
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "auto_route", "err": repr(e)[:600]})

    # ---- phase 3: per-size throughput sweeps (16x16, 25x25) ---------------
    for size, batch, depth, iters in (
        (16, 2048, (64, 256), 16384),
        (25, 512, None, 65536),
    ):
        if should_stop():
            break
        try:
            bs = np.load(corpus_path(size, batch))["boards"]
            for waves in (1, 2, 3):
                run_config(
                    size, bs, f"{size}x{size}_waves{waves}", repeats=3,
                    max_iters=iters, max_depth=depth,
                    locked_candidates=True, waves=waves, naked_pairs=False,
                )
            run_config(
                size, bs, f"{size}x{size}_waves1_pairsON", repeats=3,
                max_iters=iters, max_depth=depth,
                locked_candidates=True, waves=1, naked_pairs=True,
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": f"size{size}", "err": repr(e)[:500]})

    # ---- phase 4: serving-config splits on 9x9 ---------------------------
    if not should_stop():
        for name, kw in [
            ("9x9_pairsON", {**cfg9, "naked_pairs": True}),
            ("9x9_waves2", {**cfg9, "waves": 2}),
            ("9x9_waves4", {**cfg9, "waves": 4}),
            ("9x9_light_waves4", {**cfg9, "waves": 4, "light_waves": True}),
        ]:
            try:
                run_config(9, b9, name, repeats=3, **kw)
            except Exception as e:  # noqa: BLE001
                emit({"phase": "error", "name": name, "err": repr(e)[:500]})

    # ---- phase 5: single-board latency (blocking + amortized) -------------
    if not should_stop():
        try:
            spec = spec_for_size(9)
            # waves=1: the engine's 1-board serving path compiles
            # waves_eff = 1 when B == 1 (engine.py _run) — measure that.
            solve1 = jax.jit(
                lambda g: solve_batch(g, spec, **{**cfg9, "waves": 1})
            )
            one = jnp.asarray(b9[:1])
            dog.arm("latency1")
            jax.block_until_ready(solve1(one))
            lat = []
            for i in range(40):
                one = jnp.asarray(b9[i : i + 1])
                t0 = time.perf_counter()
                jax.block_until_ready(solve1(one))
                lat.append((time.perf_counter() - t0) * 1e3)
            lat = np.asarray(lat)
            blocking = {
                "p50_ms": round(float(np.percentile(lat, 50)), 2),
                "p95_ms": round(float(np.percentile(lat, 95)), 2),
                "min_ms": round(float(lat.min()), 2),
            }
            emit({"phase": "device_latency_1board", **blocking})
            n_async = 64
            t0 = time.perf_counter()
            outs = [solve1(jnp.asarray(b9[i : i + 1])) for i in range(n_async)]
            jax.block_until_ready(outs[-1])
            per = (time.perf_counter() - t0) / n_async * 1e3
            emit(
                {
                    "phase": "device_latency_1board_amortized",
                    "per_request_ms": round(per, 3),
                    "n": n_async,
                }
            )
            dog.disarm()
            write_artifact(
                "latency_tpu_r5.json",
                {
                    "metric": "device_solve_latency_1board_9x9",
                    "platform": platform,
                    "blocking_incl_tunnel_rtt": blocking,
                    "amortized_per_request_ms": round(per, 3),
                    "note": "blocking rows include the host<->TPU tunnel "
                    "RTT per call; the amortized row is the co-located "
                    "serving bound (VERDICT r4 task 5)",
                },
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "error", "name": "latency1", "err": repr(e)[:500]})

    # ---- phase 6: pallas compile attempt (LAST; may hang or crash) --------
    if not should_stop():
        try:
            emit({"phase": "pallas_attempt_start"})
            dog.arm("pallas_compile")
            from sudoku_solver_distributed_tpu.ops.pallas_solver import (
                solve_batch_pallas,
            )

            spec = spec_for_size(9)
            small = jnp.asarray(b9[:256])
            t0 = time.perf_counter()
            res = jax.block_until_ready(
                solve_batch_pallas(small, spec, max_depth=(32, 81))
            )
            compile_s = round(time.perf_counter() - t0, 1)
            ok = bool(np.asarray(res.solved).all())
            solve_p = jax.jit(
                lambda g: solve_batch_pallas(g, spec, max_depth=(32, 81))
            )
            jax.block_until_ready(solve_p(jnp.asarray(b9)))
            stats = time_solve(solve_p, jnp.asarray(b9), len(b9))
            dog.disarm()
            emit(
                {
                    "phase": "pallas_result",
                    "compile_s": compile_s,
                    "all_solved_256": ok,
                    **stats,
                }
            )
            write_artifact(
                "pallas_tpu_r5.json",
                {
                    "platform": platform,
                    "compile_s": compile_s,
                    "all_solved_256": ok,
                    **stats,
                },
            )
        except Exception as e:  # noqa: BLE001
            emit({"phase": "pallas_error", "err": repr(e)[:800]})

    emit(
        {
            "phase": "done",
            "reason": "session complete"
            if not should_stop()
            else "stopped at deadline",
        }
    )


if __name__ == "__main__":
    main()
