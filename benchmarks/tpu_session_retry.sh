#!/bin/bash
# Patient single-client TPU probe loop (claim discipline, docs/OPERATIONS.md):
# each attempt is ONE process that either completes the measurement session
# or dies by its own error — never killed externally. 15 min between
# attempts so a sick terminal isn't hammered with claim requests.
cd /root/repo
for i in $(seq 1 40); do
  echo "=== attempt $i $(date -u +%H:%M:%S) ===" >> benchmarks/tpu_session_r3.log
  python benchmarks/tpu_session.py >> benchmarks/tpu_session_r3.log 2>&1
  rc=$?
  echo "=== attempt $i exited rc=$rc $(date -u +%H:%M:%S) ===" >> benchmarks/tpu_session_r3.log
  if grep -q '"phase": "done"' benchmarks/tpu_session_r3.jsonl 2>/dev/null; then
    echo "=== session complete ===" >> benchmarks/tpu_session_r3.log
    exit 0
  fi
  sleep 900
done
