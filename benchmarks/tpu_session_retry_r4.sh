#!/bin/bash
# Patient single-client TPU probe loop, round 4 (claim discipline,
# docs/OPERATIONS.md): each attempt is ONE process that either completes the
# measurement session or dies by its own error — never killed externally.
# 15 min between attempts so a sick terminal isn't hammered with claims.
#
# Exits when the session reports "session complete" (all phases measured) or
# the stop flag / STOP_AT deadline inside tpu_session.py fires. The round-3
# wrapper may still be running; tpu_session.py's flock makes the overlap
# harmless (the loser skips its attempt). To relaunch after a manual stop,
# remove benchmarks/tpu_stop AND the trailing done markers in
# benchmarks/tpu_session_r4.jsonl (the grep below would otherwise exit
# immediately on the stale marker).
cd /root/repo
for i in $(seq 1 40); do
  echo "=== attempt $i $(date -u +%H:%M:%S) ===" >> benchmarks/tpu_session_r4.log
  python benchmarks/tpu_session.py >> benchmarks/tpu_session_r4.log 2>&1
  rc=$?
  echo "=== attempt $i exited rc=$rc $(date -u +%H:%M:%S) ===" >> benchmarks/tpu_session_r4.log
  if grep -q '"phase": "done"' benchmarks/tpu_session_r4.jsonl 2>/dev/null; then
    echo "=== session finished (done marker) ===" >> benchmarks/tpu_session_r4.log
    exit 0
  fi
  sleep 900
done
