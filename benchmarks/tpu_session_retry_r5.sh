#!/bin/bash
# Patient single-client TPU probe loop, round 5 (claim discipline,
# docs/OPERATIONS.md): each attempt is ONE process that either completes
# the measurement session or dies by its own watchdog — never killed
# externally.
#
# Round-5 discovery (benchmarks/tpu_session_r5.jsonl, attempt 1): the axon
# platform's terminal services are RELAY-FORWARDED local ports that come
# and go — 8082 (claim/bincode) accepted at 03:49 UTC and init took 0.1 s,
# but the compile RPC (POST 127.0.0.1:8093/remote_compile) died with
# "Connection refused" ~30 min later: the window closed mid-session. So
# this wrapper is a cheap PORT SCANNER: it TCP-probes the claim and
# compile ports every 20 s, launches the (flock-guarded) session only
# when BOTH accept, and logs every open/close transition — the
# window-availability timeline is itself a round artifact. A failed
# attempt backs off briefly and the scan resumes; the session's own
# watchdogs (init 1500 s, per-phase 2400 s) bound each attempt.
cd /root/repo
LOG=benchmarks/tpu_session_r5.log
state=closed
attempt=0
probe() { (echo >"/dev/tcp/127.0.0.1/$1") 2>/dev/null; }
while true; do
  if grep -q '"phase": "done"' benchmarks/tpu_session_r5.jsonl 2>/dev/null; then
    echo "=== session finished (done marker) $(date -u +%H:%M:%S) ===" >> "$LOG"
    exit 0
  fi
  if probe 8082 && probe 8093; then
    if [ "$state" = closed ]; then
      echo "=== window OPEN (8082+8093 accepting) $(date -u +%H:%M:%S) ===" >> "$LOG"
      state=open
    fi
    attempt=$((attempt + 1))
    echo "=== attempt $attempt $(date -u +%H:%M:%S) ===" >> "$LOG"
    t0=$(date +%s)
    python benchmarks/tpu_session_r5.py >> "$LOG" 2>&1
    rc=$?
    dur=$(( $(date +%s) - t0 ))
    echo "=== attempt $attempt exited rc=$rc after ${dur}s $(date -u +%H:%M:%S) ===" >> "$LOG"
    sleep 30
  else
    if [ "$state" = open ]; then
      echo "=== window CLOSED $(date -u +%H:%M:%S) ===" >> "$LOG"
      state=closed
    fi
    sleep 20
  fi
done
