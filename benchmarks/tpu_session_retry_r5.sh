#!/bin/bash
# Patient single-client TPU probe loop, round 5 (claim discipline,
# docs/OPERATIONS.md): each attempt is ONE process that either completes
# the measurement session or dies by its own watchdog — never killed
# externally.
#
# Round-5 discovery (benchmarks/tpu_session_r5.jsonl, attempt 1): the axon
# platform's terminal services are RELAY-FORWARDED local ports that come
# and go — 8082 (claim/bincode) accepted at 03:49 UTC and init took 0.1 s,
# but the compile RPC (POST 127.0.0.1:8093/remote_compile) died with
# "Connection refused" ~30 min later: the window closed mid-session. So
# this wrapper is a cheap PORT SCANNER probing every 20 s and logging
# every open/close transition (the availability timeline is itself a
# round artifact).
#
# Two ways to run the session when the claim port (8082) answers:
#   1. AOT: if a quick probe (benchmarks/aot_probe.py) shows client-side
#      AOT compilation executes on the terminal, run the session with
#      PALLAS_AXON_REMOTE_COMPILE=0 — no 8093 dependency at all. The
#      probe runs ONCE per window (it claims the terminal briefly;
#      re-running it every scan tick would churn the claim and pollute
#      the jsonl — the checked flag resets on the CLOSED transition).
#   2. Remote-compile: else, if 8093 also answers, run it normally.
# A failed attempt backs off briefly and the scan resumes; the session's
# own watchdogs (init 1500 s, per-phase 2400 s) bound each attempt.
cd /root/repo
LOG=benchmarks/tpu_session_r5.log
state=closed
aot_checked=no
aot=no
attempt=0
probe() { (echo >"/dev/tcp/127.0.0.1/$1") 2>/dev/null; }
STOP_AT=${TPU_SESSION_STOP_AT:-1785502000}
while true; do
  if grep -q '"phase": "done"' benchmarks/tpu_session_r5.jsonl 2>/dev/null; then
    echo "=== session finished (done marker) $(date -u +%H:%M:%S) ===" >> "$LOG"
    exit 0
  fi
  if [ "$(date +%s)" -ge "$STOP_AT" ]; then
    # hard deadline even if no window ever opened: the scan must not
    # contend with the driver's own end-of-round bench run
    echo "=== scanner stopped at deadline $(date -u +%H:%M:%S) ===" >> "$LOG"
    exit 0
  fi
  if probe 8082; then
    if [ "$state" = closed ]; then
      echo "=== window OPEN (8082 accepting) $(date -u +%H:%M:%S) ===" >> "$LOG"
      state=open
      aot_checked=no
    fi
    if [ "$aot_checked" = no ]; then
      echo "=== aot probe $(date -u +%H:%M:%S) ===" >> "$LOG"
      if PALLAS_AXON_REMOTE_COMPILE=0 python benchmarks/aot_probe.py >> "$LOG" 2>&1; then
        aot=yes
      else
        aot=no
      fi
      aot_checked=yes
      echo "=== aot probe result: $aot $(date -u +%H:%M:%S) ===" >> "$LOG"
    fi
    mode=""
    if [ "$aot" = yes ]; then
      mode="AOT"
    elif probe 8093; then
      mode="remote-compile"
    else
      sleep 20
      continue
    fi
    attempt=$((attempt + 1))
    echo "=== attempt $attempt ($mode) $(date -u +%H:%M:%S) ===" >> "$LOG"
    t0=$(date +%s)
    if [ "$mode" = AOT ]; then
      PALLAS_AXON_REMOTE_COMPILE=0 python benchmarks/tpu_session_r5.py >> "$LOG" 2>&1
    else
      python benchmarks/tpu_session_r5.py >> "$LOG" 2>&1
    fi
    rc=$?
    dur=$(( $(date +%s) - t0 ))
    echo "=== attempt $attempt exited rc=$rc after ${dur}s $(date -u +%H:%M:%S) ===" >> "$LOG"
    sleep 30
  else
    if [ "$state" = open ]; then
      echo "=== window CLOSED $(date -u +%H:%M:%S) ===" >> "$LOG"
      state=closed
    fi
    sleep 20
  fi
done
