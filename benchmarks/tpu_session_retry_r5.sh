#!/bin/bash
# Patient single-client TPU probe loop, round 5 (claim discipline,
# docs/OPERATIONS.md): each attempt is ONE process that either completes
# the measurement session or dies by its own watchdog — never killed
# externally.
#
# Round-5 change (VERDICT r4 weak #1): assume the claim window is short.
# The session's init watchdog waits 1500 s (the process sits IN LINE for
# the claim rather than giving up at 420 s), and the inter-attempt sleep
# is adaptive: a quick death (raise — sick terminal) backs off 600 s so
# the terminal isn't hammered; a watchdog death (full patient wait) retries
# after only 60 s, so the chip is being waited on ~95% of the round.
#
# Exits when the session writes a "done" marker (all phases measured or
# the STOP_AT deadline inside tpu_session_r5.py fired).
cd /root/repo
for i in $(seq 1 200); do
  echo "=== attempt $i $(date -u +%H:%M:%S) ===" >> benchmarks/tpu_session_r5.log
  t0=$(date +%s)
  python benchmarks/tpu_session_r5.py >> benchmarks/tpu_session_r5.log 2>&1
  rc=$?
  dur=$(( $(date +%s) - t0 ))
  echo "=== attempt $i exited rc=$rc after ${dur}s $(date -u +%H:%M:%S) ===" \
    >> benchmarks/tpu_session_r5.log
  if grep -q '"phase": "done"' benchmarks/tpu_session_r5.jsonl 2>/dev/null; then
    echo "=== session finished (done marker) ===" >> benchmarks/tpu_session_r5.log
    exit 0
  fi
  if [ "$dur" -lt 120 ]; then sleep 600; else sleep 60; fi
done
