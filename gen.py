"""Root shim: the reference's puzzle-generator CLI (reference gen.py:1-66).

Same contract: ``python3 gen.py N`` generates a puzzle with N blanked cells,
prints the board (zeros highlighted), then prints a ready-made curl command to
feed it to a node (reference gen.py:61-66). Generation itself is the package
generator (diagonal-box seed + backtracking completion + blanking — the
reference's own recipe, reference gen.py:31-52).
"""

import random
import sys

from sudoku_solver_distributed_tpu.api import Sudoku
from sudoku_solver_distributed_tpu.models import generate_board, oracle_solve


def solve_sudoku(board):
    """Solve in place with the host backtracker - this is NOT a distributed
    solution (reference gen.py:6-28 contract)."""
    solved = oracle_solve(board)
    if solved is None:
        return False
    for i, row in enumerate(solved):
        board[i][:] = row
    return True


def generate_sudoku(empty_boxes=0):
    """Generate a Sudoku puzzle (reference gen.py:31-52 contract)."""
    return Sudoku(generate_board(empty_boxes, rng=random.Random()))


if __name__ == "__main__":
    # positional N exactly like the reference; opt-in extensions parsed by
    # hand so the reference invocation's behavior stays byte-identical:
    #   --size 16|25   hexadoku / 25x25 (reference hardwires 9, gen.py:6-52)
    #   --seed S       deterministic generation
    #   --unique       blank cells only while the puzzle stays single-solution
    empty_boxes = int(sys.argv[1])
    args = sys.argv[2:]

    def _usage(msg):
        sys.exit(
            f"gen.py: {msg}\nusage: python3 gen.py N "
            f"[--size 16|25] [--seed S] [--unique]"
        )

    def _opt(flag, default=None):
        if flag not in args:
            return default
        idx = args.index(flag) + 1
        if idx >= len(args):
            _usage(f"{flag} needs a value")
        try:
            return int(args[idx])
        except ValueError:
            _usage(f"{flag} needs an integer, got {args[idx]!r}")

    size = _opt("--size", 9)
    seed = _opt("--seed")
    unique = "--unique" in args

    # reject leftovers: a typo ("--sizes 16", "--uniq") silently yielding a
    # default 9x9 non-unique puzzle is easy to miss in scripts, while known
    # flags already exit with usage on error — be consistently loud
    # (ADVICE r5 low)
    consumed = set()
    for flag in ("--size", "--seed"):
        if flag in args:
            idx = args.index(flag)
            consumed.update((idx, idx + 1))
    consumed.update(i for i, tok in enumerate(args) if tok == "--unique")
    leftover = [tok for i, tok in enumerate(args) if i not in consumed]
    if leftover:
        _usage(f"unknown argument(s): {' '.join(leftover)}")

    # early size validation (perfect square) — the generator's diagonal
    # fill would otherwise die with an opaque IndexError
    from sudoku_solver_distributed_tpu.ops import spec_for_size

    try:
        spec_for_size(size)
    except ValueError as e:
        _usage(str(e))

    rng = random.Random(seed)
    board = generate_board(empty_boxes, size=size, rng=rng, unique=unique)
    new_puzzle = Sudoku(board)

    print(new_puzzle)

    print(
        "curl http://localhost:8001/solve -X POST -H 'Content-Type: application/json' -d '{\"sudoku\": %s}'"
        % (new_puzzle.grid)
    )
