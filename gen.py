"""Root shim: the reference's puzzle-generator CLI (reference gen.py:1-66).

Same contract: ``python3 gen.py N`` generates a puzzle with N blanked cells,
prints the board (zeros highlighted), then prints a ready-made curl command to
feed it to a node (reference gen.py:61-66). Generation itself is the package
generator (diagonal-box seed + backtracking completion + blanking — the
reference's own recipe, reference gen.py:31-52).
"""

import random
import sys

from sudoku_solver_distributed_tpu.api import Sudoku
from sudoku_solver_distributed_tpu.models import generate_board, oracle_solve


def solve_sudoku(board):
    """Solve in place with the host backtracker - this is NOT a distributed
    solution (reference gen.py:6-28 contract)."""
    solved = oracle_solve(board)
    if solved is None:
        return False
    for i, row in enumerate(solved):
        board[i][:] = row
    return True


def generate_sudoku(empty_boxes=0):
    """Generate a Sudoku puzzle (reference gen.py:31-52 contract)."""
    return Sudoku(generate_board(empty_boxes, rng=random.Random()))


if __name__ == "__main__":
    empty_boxes = int(sys.argv[1])

    new_puzzle = generate_sudoku(empty_boxes)

    print(new_puzzle)

    print(
        "curl http://localhost:8001/solve -X POST -H 'Content-Type: application/json' -d '{\"sudoku\": %s}'"
        % (new_puzzle.grid)
    )
