"""Root shim: the reference's node CLI (reference node.py:715-730).

``python3 node.py -p 8001 -s 7001 -a localhost:7000 -h 1`` launches one P2P
node exactly as against the reference repo — same flags, same UDP protocol,
same HTTP surface — with the TPU engine behind it. See
sudoku_solver_distributed_tpu/net/cli.py for the extension flags.

Also importable for its classes, like the reference module (reference
node.py:21, 134): ``from node import P2PNode, SudokuSolver, SolverEngine``.
Everything resolves lazily (PEP 562) so ``import node`` stays free of jax
and the engine stack until an attribute is actually touched — cli.main must
parse ``--platform`` before anything initializes a backend.
"""

__all__ = ["main", "P2PNode", "SudokuSolver", "SolverEngine"]

_LAZY = {
    "main": ("sudoku_solver_distributed_tpu.net.cli", "main"),
    "P2PNode": ("sudoku_solver_distributed_tpu.net.node", "P2PNode"),
    "SudokuSolver": (
        "sudoku_solver_distributed_tpu.net.solver_api",
        "SudokuSolver",
    ),
    "SolverEngine": ("sudoku_solver_distributed_tpu.engine", "SolverEngine"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'node' has no attribute {name!r}")


if __name__ == "__main__":
    from sudoku_solver_distributed_tpu.net.cli import main

    main()
