"""Root shim: the reference's node CLI (reference node.py:715-730).

``python3 node.py -p 8001 -s 7001 -a localhost:7000 -h 1`` launches one P2P
node exactly as against the reference repo — same flags, same UDP protocol,
same HTTP surface — with the TPU engine behind it. See
sudoku_solver_distributed_tpu/net/cli.py for the extension flags.
"""

from sudoku_solver_distributed_tpu.net.cli import main

if __name__ == "__main__":
    main()
