"""Root shim: the reference's ``sudoku.py`` surface (reference sudoku.py:1-163).

``from sudoku import Sudoku`` works exactly as against the reference repo; the
class itself lives in sudoku_solver_distributed_tpu.api and validates through
the batched TPU kernels. The __main__ smoke block mirrors the reference's
(reference sudoku.py:143-163): validate a known-correct board and report.
"""

from sudoku_solver_distributed_tpu.api import Sudoku

__all__ = ["Sudoku"]


if __name__ == "__main__":
    sudoku = Sudoku(
        [
            [8, 9, 7, 1, 2, 4, 6, 3, 5],
            [5, 3, 1, 6, 7, 9, 2, 8, 4],
            [6, 4, 2, 3, 8, 5, 1, 7, 9],
            [1, 5, 4, 2, 9, 3, 8, 6, 7],
            [2, 8, 9, 7, 1, 6, 4, 5, 3],
            [3, 7, 6, 4, 5, 8, 9, 1, 2],
            [9, 2, 3, 8, 6, 7, 5, 4, 1],
            [7, 6, 5, 9, 4, 1, 3, 2, 8],
            [4, 1, 8, 5, 3, 2, 7, 9, 6],
        ]
    )

    print(sudoku)

    if sudoku.check():
        print("Sudoku is correct!")
    else:
        print("Sudoku is incorrect! Please check your solution.")
