"""sudoku_solver_distributed_tpu — a TPU-native distributed sudoku-solving framework.

A from-scratch JAX/XLA re-design of the capabilities of
``cristiano-nicolau/sudoku_solver_distributed`` (reference mounted at
/root/reference): the same ``/solve`` / ``/stats`` / ``/network`` HTTP surface and
7-type UDP JSON peer protocol (reference README.md:29-79), but the solving engine
is a batched bitmask constraint-propagation + speculative-DFS kernel running on a
TPU device mesh instead of the reference's per-cell greedy CPU task farm
(reference node.py:76-80, node.py:427-475).

Layout:
  ops/       batched board encoding, validation, propagation, branching kernels
  models/    trusted CPU oracle solver, puzzle generator, board specs
  parallel/  device-mesh execution: data-parallel solve, sharded search frontier
  net/       P2P wire protocol, membership, stats gossip, HTTP API, CLI
  utils/     handicap rate limiter, board rendering, logging
  api.py     the `Sudoku` host-facing class (reference sudoku.py:5-140 surface)
"""

__version__ = "0.1.0"
