"""graftcheck: AST-based static analyzers gating this repo's build.

The stack grew from the paper's 960-LoC single-file node into a ~9k-LoC
threaded serving system: a dozen ``threading.Lock``/``Condition``
instances across the node, coalescer, admission and membership layers, a
JAX device hot path, and a hand-rolled UDP/HTTP JSON protocol whose
producers and consumers can silently drift (the goodbye-vs-rumor
port-only bug fixed in PR 2 was exactly that class). These analyzers
mechanically prove the invariants the serving PRs established by hand,
so the next cross-thread or cross-host feature cannot quietly regress
them — serving stacks pair schedulers with correctness tooling, not
review alone (cf. Orca's batch-scheduler invariants, PAPERS.md).

Five analyzers, all stdlib-``ast``, no third-party deps, no imports of
the code under analysis (pure source analysis — safe to run anywhere,
including hosts without jax). Since v2 they share one parsed-AST pass
and one inter-procedural call graph (``callgraph.py``), built once per
run by the runner:

  * ``locks``       — lock-discipline: lock-order cycles (per-class AND
                      cross-class along call-graph edges), blocking
                      calls while holding a lock, condition-on-foreign-
                      lock, guarded-attribute write races (LOCK1xx).
  * ``jax_hygiene`` — serving-path JAX hygiene: implicit host syncs on
                      device values, Python branches on traced values,
                      non-hashable static args, uncached jit factories
                      (JAX1xx).
  * ``wire_schema`` — wire-protocol drift: the key sets each ``wire.py``
                      constructor produces vs the keys each UDP handler
                      consumes, per message ``type`` (WIRE1xx); consumer
                      modules are auto-discovered from the call graph.
  * ``seams``       — dispatch-contract coverage: every route-core →
                      jit-invocation path, per dispatch shape, must
                      carry supervision, trace, cost, deadline and
                      fallback legs (SEAM1xx); also emits the
                      five-shape contract matrix (``--json``) the
                      planned ExecutionPlane refactor consumes.
  * ``threadctx``   — thread-context hazards: expensive or indefinitely
                      blocking work reachable on singleton loop threads
                      (UDP loop, coalescer drivers, watchdog)
                      (THREAD1xx).

Usage::

    python -m sudoku_solver_distributed_tpu.analysis            # report
    python -m sudoku_solver_distributed_tpu.analysis --strict   # gate

Library API::

    from sudoku_solver_distributed_tpu import analysis
    findings = analysis.run_analyzers(analysis.default_config())

Suppression is ONLY via the committed baseline file
(``analysis/baseline.toml``): new violations fail ``--strict`` while
baselined legacy ones stay visible debt, each entry carrying an in-file
``reason``. There are no inline suppression comments by design.
"""

from __future__ import annotations

from .findings import (  # noqa: F401
    BaselineEntry,
    Finding,
    apply_baseline,
    load_baseline,
)
from .runner import (  # noqa: F401
    AnalysisResult,
    Config,
    default_config,
    run_analysis,
    run_analyzers,
)

__all__ = [
    "AnalysisResult",
    "BaselineEntry",
    "Config",
    "Finding",
    "apply_baseline",
    "default_config",
    "load_baseline",
    "run_analysis",
    "run_analyzers",
]
