"""graftcheck: AST-based static analyzers gating this repo's build.

The stack grew from the paper's 960-LoC single-file node into a ~9k-LoC
threaded serving system: a dozen ``threading.Lock``/``Condition``
instances across the node, coalescer, admission and membership layers, a
JAX device hot path, and a hand-rolled UDP/HTTP JSON protocol whose
producers and consumers can silently drift (the goodbye-vs-rumor
port-only bug fixed in PR 2 was exactly that class). These analyzers
mechanically prove the invariants the serving PRs established by hand,
so the next cross-thread or cross-host feature cannot quietly regress
them — serving stacks pair schedulers with correctness tooling, not
review alone (cf. Orca's batch-scheduler invariants, PAPERS.md).

Three analyzers, all stdlib-``ast``, no third-party deps, no imports of
the code under analysis (pure source analysis — safe to run anywhere,
including hosts without jax):

  * ``locks``       — lock-discipline: lock-order cycles, blocking calls
                      while holding a lock, condition-on-foreign-lock,
                      guarded-attribute write races (LOCK1xx).
  * ``jax_hygiene`` — serving-path JAX hygiene: implicit host syncs on
                      device values, Python branches on traced values,
                      non-hashable static args, uncached jit factories
                      (JAX1xx).
  * ``wire_schema`` — wire-protocol drift: the key sets each ``wire.py``
                      constructor produces vs the keys each UDP handler
                      consumes, per message ``type`` (WIRE1xx).

Usage::

    python -m sudoku_solver_distributed_tpu.analysis            # report
    python -m sudoku_solver_distributed_tpu.analysis --strict   # gate

Library API::

    from sudoku_solver_distributed_tpu import analysis
    findings = analysis.run_analyzers(analysis.default_config())

Suppression is ONLY via the committed baseline file
(``analysis/baseline.toml``): new violations fail ``--strict`` while
baselined legacy ones stay visible debt, each entry carrying an in-file
``reason``. There are no inline suppression comments by design.
"""

from __future__ import annotations

from .findings import (  # noqa: F401
    BaselineEntry,
    Finding,
    apply_baseline,
    load_baseline,
)
from .runner import (  # noqa: F401
    Config,
    default_config,
    run_analyzers,
)

__all__ = [
    "BaselineEntry",
    "Config",
    "Finding",
    "apply_baseline",
    "default_config",
    "load_baseline",
    "run_analyzers",
]
