"""CLI: ``python -m sudoku_solver_distributed_tpu.analysis [--strict]``.

Exit codes: 0 — no unsuppressed error-severity findings (warnings and
baselined debt are printed but never fail); 1 — unsuppressed errors
exist AND ``--strict`` was given; 2 — the baseline file itself is
invalid (always fatal: an unauditable suppression list means the gate
isn't gating).

The default (non-strict) run is a report: it prints everything and
exits 0, so operators can look at debt without wiring the exit code
into anything. CI runs ``--strict`` (.github/workflows/ci.yml).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from .findings import apply_baseline, load_baseline
from .runner import Config, default_config, run_analysis
from .sarif import to_sarif
from .seams import MATRIX_SCHEMA_VERSION

# rule-id prefix per analyzer: a partial --rules run must only judge the
# baseline entries its analyzers could have re-confirmed
_RULE_PREFIXES = {
    "locks": "LOCK",
    "jax": "JAX",
    "wire": "WIRE",
    "seams": "SEAM",
    "thread": "THREAD",
}

# --json output contract (pinned by test_cli_json_schema_pinned): the
# ExecutionPlane tooling consumes contract_matrix, so additions bump
# JSON_SCHEMA_VERSION and removals/renames are breaking
JSON_SCHEMA_VERSION = 2
_JSON_KEYS = (
    "schema_version",
    "errors",
    "warnings",
    "suppressed",
    "stale_baseline",
    "contract_matrix",
    "wire_consumers",
)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sudoku_solver_distributed_tpu.analysis",
        description=(
            "graftcheck: lock-discipline, JAX-hygiene, wire-schema, "
            "dispatch-seam and thread-context static analysis for "
            "this repo"
        ),
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="exit 1 on any unsuppressed error-severity finding",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="machine-readable output (one JSON object)",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help="baseline file (default: analysis/baseline.toml; "
        "'none' disables suppression)",
    )
    parser.add_argument(
        "--package",
        type=Path,
        default=None,
        help="package tree to analyze instead of this repo's (fixture "
        "trees in tests use this); findings are reported relative to "
        "its parent",
    )
    parser.add_argument(
        "--rules",
        default="locks,jax,wire,seams,thread",
        help="comma-separated analyzer subset "
        "(locks,jax,wire,seams,thread)",
    )
    parser.add_argument(
        "--sarif",
        type=Path,
        default=None,
        help="also write findings as SARIF 2.1.0 to this path "
        "(uploaded by CI so findings annotate PRs inline)",
    )
    args = parser.parse_args(argv)

    rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
    unknown = [r for r in rules if r not in _RULE_PREFIXES]
    if unknown or not rules:
        # a typo'd subset must not silently run ZERO analyzers and
        # report green — that is a gate that gates nothing
        parser.error(
            f"unknown analyzer(s) {unknown or '(none)'} — valid: "
            f"{sorted(_RULE_PREFIXES)}"
        )

    cfg = default_config()
    package = (args.package or cfg.package).resolve()
    if args.package is not None:
        # fixture mode: report relative to the tree's parent, and use
        # its own baseline (if any) unless one was given explicitly
        default_baseline = package / "analysis" / "baseline.toml"
        root = package.parent
    else:
        default_baseline = cfg.baseline
        root = cfg.root
    cfg = Config(
        root=root,
        package=package,
        serving=cfg.serving,
        wire_producer=cfg.wire_producer,
        wire_consumers=cfg.wire_consumers,
        baseline=(
            None
            if str(args.baseline) == "none"
            else (args.baseline or default_baseline)
        ),
        analyzers=rules,
    )

    result = run_analysis(cfg)
    findings = result.findings
    try:
        entries = (
            load_baseline(cfg.baseline) if cfg.baseline is not None else []
        )
    except ValueError as e:
        print(f"graftcheck: invalid baseline: {e}", file=sys.stderr)
        return 2
    active, suppressed, stale = apply_baseline(findings, entries)
    # an entry can only be stale if the analyzer that would re-confirm it
    # actually ran: `--rules locks` must not report the jax/wire entries
    # as "debt paid — delete it" and talk someone into deleting them
    ran_prefixes = tuple(_RULE_PREFIXES[r] for r in cfg.analyzers)
    stale = [e for e in stale if e.rule.startswith(ran_prefixes)]
    errors = [f for f in active if f.severity == "error"]
    warnings = [f for f in active if f.severity == "warning"]

    if args.sarif is not None:
        args.sarif.write_text(
            json.dumps(to_sarif(active, suppressed), indent=2) + "\n"
        )

    if args.json:
        payload = {
            "schema_version": JSON_SCHEMA_VERSION,
            "errors": [vars(f) for f in errors],
            "warnings": [vars(f) for f in warnings],
            "suppressed": [vars(f) for f in suppressed],
            "stale_baseline": [vars(e) for e in stale],
            # the five-shape × five-leg dispatch-contract inventory
            # (seams.MATRIX_SCHEMA_VERSION inside; {} if seams not run)
            "contract_matrix": result.contract_matrix,
            "wire_consumers": list(result.wire_consumers),
        }
        assert set(payload) == set(_JSON_KEYS)
        assert (
            not result.contract_matrix
            or result.contract_matrix["schema_version"]
            == MATRIX_SCHEMA_VERSION
        )
        print(json.dumps(payload, indent=2))
    else:
        for f in active:
            print(f.format())
        if suppressed:
            print(
                f"-- {len(suppressed)} baselined finding(s) "
                f"(visible debt; see analysis/baseline.toml):"
            )
            for f in suppressed:
                print(f"   {f.format()}")
        for e in stale:
            print(
                f"-- stale baseline entry (debt paid — delete it): "
                f"{e.rule} {e.path} {e.symbol}"
            )
        print(
            f"graftcheck: {len(errors)} error(s), {len(warnings)} "
            f"warning(s), {len(suppressed)} baselined, {len(stale)} "
            f"stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
        )

    if args.strict and errors:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
