"""Shared AST plumbing for the graftcheck analyzers.

Everything here is pure ``ast`` — the analyzers never import the code
they inspect, so they run identically on a TPU pod host and a bare CI
runner with no jax installed.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Tuple


class Module:
    """One parsed source file plus the lookups every analyzer needs."""

    def __init__(self, path: Path, rel_path: str):
        self.path = path
        self.rel_path = rel_path  # repo-relative posix path, for findings
        self.tree = ast.parse(path.read_text(), filename=str(path))
        # import alias → dotted module name ("np" → "numpy"); and
        # from-imports: local name → "module.attr" ("Lock" →
        # "threading.Lock")
        self.import_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or alias.name] = (
                        alias.name
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )

    def resolve_call(self, call: ast.Call) -> Optional[str]:
        """Best-effort dotted name of a call target, with import aliases
        normalized: ``_queue.Queue(...)`` → "queue.Queue", ``Lock()``
        after ``from threading import Lock`` → "threading.Lock"."""
        return self.resolve_name(call.func)

    def resolve_name(self, node: ast.AST) -> Optional[str]:
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = node.id
        if root in self.import_aliases:
            root = self.import_aliases[root]
        elif root in self.from_imports:
            root = self.from_imports[root]
        parts.append(root)
        return ".".join(reversed(parts))

    def classes(self) -> Iterator[ast.ClassDef]:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                yield node

    def functions(self) -> Iterator[ast.FunctionDef]:
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


def iter_modules(root: Path, rel_to: Path) -> Iterator[Module]:
    """Parse every .py under ``root`` (skipping caches), reporting paths
    relative to ``rel_to``. Syntax errors propagate — an unparseable
    file must fail the gate loudly, not vanish from coverage."""
    for path in sorted(root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        yield Module(path, path.relative_to(rel_to).as_posix())


def self_attr(node: ast.AST) -> Optional[str]:
    """"X" for a ``self.X`` expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def call_kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def methods_of(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    out: Dict[str, ast.FunctionDef] = {}
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def decorator_names(fn: ast.FunctionDef, mod: Module) -> List[str]:
    """Dotted names of a function's decorators (call decorators resolve
    to their callee: ``@lru_cache(maxsize=None)`` → "functools.lru_cache")."""
    names = []
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        resolved = mod.resolve_name(target)
        if resolved:
            names.append(resolved)
    return names


def names_in(node: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(node) if isinstance(n, ast.Name)]


def assign_targets(stmt: ast.stmt) -> List[Tuple[ast.expr, ast.expr]]:
    """(target, value) pairs for plain/annotated/augmented assignments,
    with tuple targets flattened pairwise where the value is a matching
    tuple, else each element paired with the whole value."""
    pairs: List[Tuple[ast.expr, ast.expr]] = []
    if isinstance(stmt, ast.Assign):
        for target in stmt.targets:
            pairs.extend(_flatten(target, stmt.value))
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        pairs.extend(_flatten(stmt.target, stmt.value))
    elif isinstance(stmt, ast.AugAssign):
        pairs.append((stmt.target, stmt.value))
    return pairs


def _flatten(
    target: ast.expr, value: ast.expr
) -> List[Tuple[ast.expr, ast.expr]]:
    if isinstance(target, (ast.Tuple, ast.List)):
        if (
            isinstance(value, (ast.Tuple, ast.List))
            and len(value.elts) == len(target.elts)
        ):
            out = []
            for t, v in zip(target.elts, value.elts):
                out.extend(_flatten(t, v))
            return out
        return [(t, value) for t in target.elts]
    return [(target, value)]
