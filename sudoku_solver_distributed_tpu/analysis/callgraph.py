"""Package-wide call graph + parsed-AST cache (graftcheck v2 core).

Every inter-procedural rule family (SEAM1xx dispatch-contract seams,
THREAD1xx thread-context hazards, cross-class LOCK106 ordering) runs on
ONE shared :class:`CallGraph` built from the runner's single parse pass —
analyzers never re-read or re-parse source, which is what keeps the whole
gate inside its ~2 s budget.

Resolution model (deliberately CHA-like, documented so findings can be
audited against it):

  * ``self.method()`` resolves inside the enclosing class only — this
    codebase composes objects rather than inheriting across modules, so
    a miss means a dynamic attribute (jit program, injected hook) and
    produces no edge.
  * bare ``name()`` prefers a definition in the same module (top-level
    or nested), then falls back to same-named top-level functions
    anywhere in the package.
  * ``recv.method()`` on any other receiver resolves by METHOD NAME to
    every same-named definition in the package (class-hierarchy-analysis
    style), except when the receiver resolves to a known external import
    (``threading.*``, ``np.*`` …). Names defined more than
    ``MAX_FANOUT`` times are too generic to resolve and produce no
    edges — precision over recall: an analyzer edge that sprays is
    worse than one that misses.
  * a ``lambda`` passed as a call argument is ALSO attributed to the
    callee when the callee resolves into the package (higher-order
    idiom: ``_supervised_answer(sup, arr, lambda: submit(...))`` runs
    the lambda inside ``_supervised_answer``, so the submit edge
    belongs on it); its calls stay on the enclosing function too,
    marked deferred.
  * nested ``def``s are their own nodes (``outer.inner``) — they are
    thread targets and deferred callbacks, not part of the enclosing
    body's synchronous flow. Being closures, they resolve ONLY from
    their enclosing function (or sibling nested defs), never by
    package-wide name.

Thread construction sites (``threading.Thread(...)``) are indexed with
their target, constant ``name=`` (or the fact it was dynamic), whether
the spawn sits inside a loop statement (pool idiom), and whether the
handle is kept on ``self`` (singleton idiom) — threadctx.py classifies
loop threads from exactly these facts.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ._astutil import Module, call_kw, const_str, self_attr

# a callee name defined more than this many times package-wide is too
# generic (get/put/append territory) to resolve by name
MAX_FANOUT = 6

# receiver roots that mark a call as external (stdlib / third-party):
# resolve_name() normalizes import aliases, so these are real module
# names, not whatever the file aliased them to
_EXTERNAL_ROOTS = {
    "numpy", "jax", "jaxlib", "np", "jnp", "threading", "queue", "socket",
    "time", "logging", "os", "sys", "json", "math", "heapq", "collections",
    "functools", "itertools", "struct", "random", "dataclasses", "argparse",
    "signal", "gc", "http", "socketserver", "urllib", "contextlib", "enum",
    "pathlib", "typing", "traceback", "uuid", "hashlib", "concurrent",
    "subprocess", "shutil", "tempfile", "re", "io", "csv", "base64",
}


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""

    name: str                 # final callee name ("submit", "mark", …)
    kind: str                 # "self" | "name" | "attr"
    dotted: Optional[str]     # resolve_call() result, if any
    line: int
    deferred: bool            # lexically inside a lambda
    call: ast.Call


@dataclasses.dataclass
class ThreadSpawn:
    """One ``threading.Thread(...)`` construction site."""

    owner: str                    # key of the constructing function
    path: str
    line: int
    target: Optional[str]         # final name of the target callable
    thread_name: Optional[str]    # constant name= string, else None
    dynamic_name: bool            # name= present but not a constant
    in_loop: bool                 # constructed inside for/while (pool idiom)
    on_self: bool                 # handle kept on self.X (singleton idiom)


class FuncNode:
    """One function/method definition plus everything the rule families
    ask about it."""

    def __init__(
        self,
        mod: Module,
        fn: ast.FunctionDef,
        symbol: str,
        cls_name: Optional[str],
        nested: bool = False,
    ):
        self.mod = mod
        self.fn = fn
        self.symbol = symbol
        self.cls_name = cls_name
        self.nested = nested
        self.key = f"{mod.rel_path}::{symbol}"
        self.calls: List[CallSite] = []
        self.spawns: List[ThreadSpawn] = []
        self.has_while = False
        self._idents: Optional[Set[str]] = None

    @property
    def identifiers(self) -> Set[str]:
        """Every Name id and Attribute attr appearing in the body —
        the marker predicates (seams.py) match against this."""
        if self._idents is None:
            idents: Set[str] = set()
            for node in ast.walk(self.fn):
                if isinstance(node, ast.Name):
                    idents.add(node.id)
                elif isinstance(node, ast.Attribute):
                    idents.add(node.attr)
            self._idents = idents
        return self._idents

    @property
    def call_names(self) -> Set[str]:
        return {c.name for c in self.calls}

    def params(self) -> List[str]:
        a = self.fn.args
        return [
            p.arg
            for p in (a.posonlyargs + a.args + a.kwonlyargs)
        ]

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<FuncNode {self.key}>"


def _direct_nested_defs(fn: ast.FunctionDef) -> List[ast.FunctionDef]:
    """FunctionDefs nested directly under ``fn`` (not inside a deeper
    def)."""
    out: List[ast.FunctionDef] = []

    def scan(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
                continue  # deeper defs belong to this child
            scan(child)

    scan(fn)
    return out


class CallGraph:
    """The shared inter-procedural index over one parsed package."""

    def __init__(self, modules: Sequence[Module]):
        self.modules = list(modules)
        self.by_rel_pkg: Dict[str, Module] = {}
        self.nodes: Dict[str, FuncNode] = {}
        # final name -> node keys (methods and functions)
        self.by_name: Dict[str, List[str]] = {}
        # (rel_path, class) -> {method name -> key}
        self.methods: Dict[Tuple[str, str], Dict[str, str]] = {}
        # rel_path -> {bare function name -> key} (top-level + nested)
        self.module_funcs: Dict[str, Dict[str, str]] = {}
        for mod in self.modules:
            self._index_module(mod)
        self._reattribute_lambdas()
        self.edges: Dict[str, List[Tuple[str, CallSite]]] = {}
        for key, node in self.nodes.items():
            out: List[Tuple[str, CallSite]] = []
            for site in node.calls:
                for target in self._resolve_site(node, site):
                    out.append((target, site))
            self.edges[key] = out
        self.spawns: List[ThreadSpawn] = [
            s for n in self.nodes.values() for s in n.spawns
        ]

    # -- indexing ----------------------------------------------------------

    def _index_module(self, mod: Module) -> None:
        mfuncs: Dict[str, str] = {}
        self.module_funcs[mod.rel_path] = mfuncs

        def add(
            fn: ast.FunctionDef,
            symbol: str,
            cls: Optional[str],
            nested: bool = False,
        ):
            node = FuncNode(mod, fn, symbol, cls, nested=nested)
            self.nodes[node.key] = node
            if not nested:
                # a nested def is a closure: callable only from its
                # enclosing function, so it must NOT participate in
                # package-wide name/attr resolution (a CHA edge from
                # some set's .add() to a helper named add sprays the
                # whole graph)
                self.by_name.setdefault(fn.name, []).append(node.key)
                mfuncs.setdefault(fn.name, node.key)
            _BodyWalker(mod, node).run()
            for sub in _direct_nested_defs(fn):
                add(sub, f"{symbol}.{sub.name}", cls, nested=True)
            return node

        for stmt in mod.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(stmt, stmt.name, None)
            elif isinstance(stmt, ast.ClassDef):
                methods: Dict[str, str] = {}
                self.methods[(mod.rel_path, stmt.name)] = methods
                for sub in stmt.body:
                    if isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        node = add(
                            sub, f"{stmt.name}.{sub.name}", stmt.name
                        )
                        methods[sub.name] = node.key

    def _reattribute_lambdas(self) -> None:
        """A lambda passed as an argument to a resolvable package callee
        runs inside that callee (higher-order idiom) — copy its calls
        onto the callee node so dispatch paths flow THROUGH it."""
        for node in list(self.nodes.values()):
            for site in list(node.calls):
                lambdas = [
                    a
                    for a in (
                        list(site.call.args)
                        + [kw.value for kw in site.call.keywords]
                    )
                    if isinstance(a, ast.Lambda)
                ]
                if not lambdas:
                    continue
                targets = self._resolve_site(node, site)
                if not targets:
                    continue
                inner: List[CallSite] = []
                for lam in lambdas:
                    for sub in ast.walk(lam.body):
                        if not isinstance(sub, ast.Call):
                            continue
                        func = sub.func
                        dotted = node.mod.resolve_call(sub)
                        if isinstance(func, ast.Name):
                            inner.append(
                                CallSite(
                                    func.id, "name", dotted,
                                    sub.lineno, True, sub,
                                )
                            )
                        elif isinstance(func, ast.Attribute):
                            # the lambda's ``self`` is the ENCLOSING
                            # instance, not the callee's — resolve
                            # globally, never against the callee class
                            inner.append(
                                CallSite(
                                    func.attr, "attr", dotted,
                                    sub.lineno, True, sub,
                                )
                            )
                for target in targets:
                    self.nodes[target].calls.extend(inner)

    # -- resolution --------------------------------------------------------

    def _resolve_site(
        self, node: FuncNode, site: CallSite
    ) -> List[str]:
        if site.dotted is not None:
            root = site.dotted.split(".", 1)[0]
            if root in _EXTERNAL_ROOTS:
                return []
        if site.kind == "self":
            if node.cls_name is not None:
                methods = self.methods.get(
                    (node.mod.rel_path, node.cls_name), {}
                )
                if site.name in methods:
                    return [methods[site.name]]
            return []
        if site.kind == "name":
            # nested defs first: callable from the enclosing function
            # (or a sibling nested def) only
            nested_child = f"{node.key}.{site.name}"
            if nested_child in self.nodes:
                return [nested_child]
            if node.nested:
                sibling = (
                    f"{node.key.rsplit('.', 1)[0]}.{site.name}"
                )
                if sibling in self.nodes:
                    return [sibling]
            local = self.module_funcs.get(node.mod.rel_path, {})
            if site.name in local and local[site.name] != node.key:
                return [local[site.name]]
        candidates = self.by_name.get(site.name, [])
        if not candidates or len(candidates) > MAX_FANOUT:
            return []
        if site.kind == "name":
            # bare-name fallback: module-level functions only
            candidates = [
                k for k in candidates if self.nodes[k].cls_name is None
            ]
        return [k for k in candidates if k != node.key]

    # -- queries -----------------------------------------------------------

    def callees(self, key: str) -> List[Tuple[str, CallSite]]:
        return self.edges.get(key, [])

    def reachable(
        self, roots: Iterable[str], include_deferred: bool = True
    ) -> Set[str]:
        """Every node key reachable from ``roots`` along call edges."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.nodes]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            for target, site in self.edges.get(key, ()):
                if not include_deferred and site.deferred:
                    continue
                if target not in seen:
                    stack.append(target)
        return seen

    def find(self, rel_path_suffix: str, symbol: str) -> Optional[str]:
        """Node key for (path suffix, symbol), e.g.
        ``find("net/node.py", "P2PNode.run")`` — suffix-matched so
        callers don't care about the package prefix."""
        for key, node in self.nodes.items():
            if node.symbol == symbol and node.mod.rel_path.endswith(
                rel_path_suffix
            ):
                return key
        return None

    def paths(
        self,
        entry: str,
        sinks: Set[str],
        extra_edges: Optional[Dict[str, List[str]]] = None,
        max_paths: int = 16,
        max_depth: int = 24,
    ) -> List[List[str]]:
        """Up to ``max_paths`` simple paths entry→any sink over call
        edges plus ``extra_edges`` (declared queue/condition handoffs)."""
        extra = extra_edges or {}
        # restrict the DFS to nodes from which a sink is reachable —
        # without this the search wanders the whole call web under the
        # entry before finding anything
        rev: Dict[str, List[str]] = {}
        for src, outs in self.edges.items():
            for target, _site in outs:
                rev.setdefault(target, []).append(src)
        for src, outs2 in extra.items():
            for target in outs2:
                rev.setdefault(target, []).append(src)
        allowed: Set[str] = set()
        stack = [s for s in sinks]
        while stack:
            key = stack.pop()
            if key in allowed:
                continue
            allowed.add(key)
            stack.extend(rev.get(key, ()))
        out: List[List[str]] = []

        def step(key: str, trail: List[str]):
            if len(out) >= max_paths or len(trail) > max_depth:
                return
            trail = trail + [key]
            if key in sinks:
                out.append(trail)
                return
            nexts = [t for t, _s in self.edges.get(key, ())]
            nexts += extra.get(key, [])
            seen_next: Set[str] = set()
            for target in nexts:
                if (
                    target in trail
                    or target in seen_next
                    or target not in allowed
                ):
                    continue
                seen_next.add(target)
                step(target, trail)

        if entry in self.nodes:
            step(entry, [])
        return out


class _BodyWalker:
    """Collect call sites + thread spawns for one function body,
    pruning nested defs (own nodes) and marking lambda bodies
    deferred."""

    def __init__(self, mod: Module, node: FuncNode):
        self.mod = mod
        self.node = node
        # lines of Thread(...) calls assigned to self.X in this body
        self._self_assigned_lines: Set[int] = set()

    def run(self) -> None:
        fn = self.node.fn
        for stmt in fn.body:
            self._mark_self_assigns(stmt)
        for stmt in fn.body:
            self._walk(stmt, deferred=False, in_loop=False)

    def _mark_self_assigns(self, stmt: ast.AST) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if any(
                    self_attr(t) is not None for t in node.targets
                ):
                    self._self_assigned_lines.add(node.value.lineno)

    def _walk(self, node: ast.AST, deferred: bool, in_loop: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested def: its own FuncNode
        if isinstance(node, ast.Lambda):
            self._walk(node.body, deferred=True, in_loop=in_loop)
            return
        if isinstance(node, ast.While):
            self.node.has_while = True
            in_loop = True
        elif isinstance(node, ast.For):
            in_loop = True
        if isinstance(node, ast.Call):
            self._record_call(node, deferred, in_loop)
        for child in ast.iter_child_nodes(node):
            self._walk(child, deferred, in_loop)

    def _record_call(
        self, call: ast.Call, deferred: bool, in_loop: bool
    ) -> None:
        dotted = self.mod.resolve_call(call)
        if dotted == "threading.Thread":
            self._record_spawn(call, in_loop)
        func = call.func
        site: Optional[CallSite] = None
        if isinstance(func, ast.Name):
            site = CallSite(
                func.id, "name", dotted, call.lineno, deferred, call
            )
        elif isinstance(func, ast.Attribute):
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
            ):
                kind = "self"
            else:
                kind = "attr"
            site = CallSite(
                func.attr, kind, dotted, call.lineno, deferred, call
            )
        if site is not None:
            self.node.calls.append(site)

    def _record_spawn(self, call: ast.Call, in_loop: bool) -> None:
        target_expr = call_kw(call, "target")
        target: Optional[str] = None
        if isinstance(target_expr, ast.Name):
            target = target_expr.id
        elif isinstance(target_expr, ast.Attribute):
            target = target_expr.attr
        name_expr = call_kw(call, "name")
        thread_name = const_str(name_expr) if name_expr is not None else None
        self.node.spawns.append(
            ThreadSpawn(
                owner=self.node.key,
                path=self.mod.rel_path,
                line=call.lineno,
                target=target,
                thread_name=thread_name,
                dynamic_name=(
                    name_expr is not None and thread_name is None
                ),
                in_loop=in_loop,
                on_self=call.lineno in self._self_assigned_lines,
            )
        )


def build_graph(modules: Sequence[Module]) -> CallGraph:
    return CallGraph(modules)
