"""Finding records and the baseline suppression file.

A finding is keyed for suppression by (rule, path, symbol) — NOT by line:
lines shift on every edit, and a baseline that rots on unrelated edits
trains people to regenerate it blindly, which defeats its purpose. The
symbol is the enclosing ``Class.method`` (or function, or ``<module>``),
so one justified entry covers all same-rule findings in that symbol —
coarse on purpose: a symbol whose design triggers a rule usually triggers
it at several sites for the same reason.

Baseline format (``analysis/baseline.toml``)::

    [[suppress]]
    rule = "LOCK102"
    path = "sudoku_solver_distributed_tpu/net/node.py"
    symbol = "P2PNode.peer_sudoku_solve"
    reason = "why this legacy violation is acceptable debt"

Every entry MUST carry a non-empty ``reason`` — an unjustified entry is a
load error, not a warning: the file is the audit trail. Entries that no
longer match anything are reported as stale so fixed debt gets deleted
rather than silently accumulating.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str       # e.g. "LOCK102"
    severity: str   # "error" | "warning"
    path: str       # repo-relative posix path
    line: int
    symbol: str     # enclosing Class.method / function / "<module>"
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} [{self.severity}] "
            f"{self.symbol}: {self.message}"
        )


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    rule: str
    path: str
    symbol: str
    reason: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)


def _parse_toml(text: str) -> dict:
    """Parse TOML with whatever the interpreter has (tomllib on 3.11+,
    tomli where installed), falling back to a minimal parser that covers
    exactly the baseline's subset: ``[[suppress]]`` array-of-tables with
    one-line ``key = "string"`` pairs. The fallback keeps the analyzers
    dependency-free on 3.10 containers — the suppression file must never
    be the reason the gate can't run."""
    try:
        import tomllib  # Python >= 3.11

        return tomllib.loads(text)
    except ImportError:
        pass
    try:
        import tomli

        return tomli.loads(text)
    except ImportError:
        pass
    tables: List[dict] = []
    current: Optional[dict] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[suppress]]":
            current = {}
            tables.append(current)
            continue
        if line.startswith("["):
            raise ValueError(
                f"baseline fallback parser: unsupported table at line "
                f"{lineno}: {line!r}"
            )
        if current is None or "=" not in line:
            raise ValueError(
                f"baseline fallback parser: cannot parse line {lineno}: "
                f"{line!r}"
            )
        key, _, value = line.partition("=")
        value = value.strip()
        if not (len(value) >= 2 and value[0] == value[-1] == '"'):
            raise ValueError(
                f"baseline fallback parser: value must be a quoted string "
                f"at line {lineno}: {line!r}"
            )
        current[key.strip()] = value[1:-1]
    return {"suppress": tables}


def load_baseline(path: Path) -> List[BaselineEntry]:
    """Load and validate the baseline file; a missing file is an empty
    baseline (the desired steady state)."""
    if not path.exists():
        return []
    data = _parse_toml(path.read_text())
    entries: List[BaselineEntry] = []
    seen: Dict[Tuple[str, str, str], int] = {}
    for i, tbl in enumerate(data.get("suppress", []), 1):
        missing = [
            k for k in ("rule", "path", "symbol", "reason") if not tbl.get(k)
        ]
        if missing:
            raise ValueError(
                f"baseline entry #{i} is missing required field(s) "
                f"{missing}: every suppression must name rule/path/symbol "
                f"and justify itself with a non-empty reason"
            )
        entry = BaselineEntry(
            rule=str(tbl["rule"]),
            path=str(tbl["path"]),
            symbol=str(tbl["symbol"]),
            reason=str(tbl["reason"]),
        )
        if entry.key() in seen:
            raise ValueError(
                f"baseline entry #{i} duplicates entry "
                f"#{seen[entry.key()]} ({entry.rule} {entry.path} "
                f"{entry.symbol})"
            )
        seen[entry.key()] = i
        entries.append(entry)
    return entries


def apply_baseline(
    findings: Sequence[Finding], entries: Sequence[BaselineEntry]
) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
    """Split findings into (active, suppressed) and return the stale
    baseline entries — entries that matched nothing, i.e. debt that was
    paid off but whose IOU was never torn up."""
    by_key = {e.key(): e for e in entries}
    matched = set()
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        if f.key() in by_key:
            matched.add(f.key())
            suppressed.append(f)
        else:
            active.append(f)
    stale = [e for e in entries if e.key() not in matched]
    return active, suppressed, stale
