"""JAX hot-path hygiene analyzer (JAX1xx) for serving-path modules.

The serving path (``engine.py``, ``parallel/``) has a hard contract: one
device→host transfer per request, at a documented sync point, on warm
pre-compiled programs. Three hazard classes silently break it:

  * an *implicit* host sync — ``np.asarray``/``np.array``/``float``/
    ``int``/``bool``/``.item()``/``jax.device_get`` applied to a device
    value — stalls the calling thread mid-pipeline where nobody expects
    a transfer; the allowed form is an explicit
    ``jax.block_until_ready`` at the documented sync point (it launders
    the taint: its result reads as host-safe);
  * a Python branch on a *traced* value inside a jitted function either
    fails at trace time or, with unhashable workarounds, forces
    retraces;
  * re-tracing hazards: ``jax.jit`` re-invoked per call in an uncached
    factory (every call builds a fresh closure → a fresh trace), and
    mutable literals passed for ``static_argnums``/``static_argnames``
    parameters (unhashable → TypeError at call time).

Rules:

  JAX101 (error)   implicit host sync on a device-derived value in a
                   serving-path module.
  JAX102 (error)   Python ``if``/``while``/``assert`` on a traced value
                   inside a jit-compiled function.
  JAX103 (error)   mutable literal (list/dict/set) passed for a static
                   jit argument.
  JAX104 (error)   ``jax.jit`` called inside a function that is neither
                   module setup (``__init__``) nor memoized with
                   ``functools.lru_cache``/``cache`` — a per-call trace.
  JAX105 (error)   host reuse of a buffer passed at a ``donate_argnums``
                   position after the donating call (PR 15 — the
                   segment program donates its carried state): the
                   donated array is DELETED the moment the call is
                   enqueued, so any later read raises "Array has been
                   deleted" at an arbitrary distance from the bug. The
                   blessed pattern rebinds the name from the call's own
                   results (``state, d, g = prog(state, ...)``); a later
                   independent rebind also launders. Tracked for plain
                   Name arguments of jit callables assigned with
                   ``donate_argnums`` (locals and ``self.X`` attrs),
                   lexically by line — the same approximation budget as
                   the other rules.

Device taint is tracked per function: calls to jit-made callables
(``self.X = jax.jit(...)`` attributes, ``name = jax.jit(...)`` locals,
and jit *factories* — functions returning jit objects, resolved to a
fixed point so ``racer = _make_racer(...)`` counts), ``jnp.*`` calls and
``jax.device_put`` are sources; attribute/subscript/arith propagate;
``.shape``/``.dtype``/``.ndim``/``.size`` are static metadata and drop
the taint, as does an explicit sync. Function parameters are untainted
by default (host arrays until proven otherwise), so ``np.asarray(board,
np.int32)``-style ingress normalization never false-positives.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ._astutil import Module, assign_targets, decorator_names, self_attr
from .findings import Finding

_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_STATIC_META = {"shape", "dtype", "ndim", "size", "sharding"}
_MEMO_DECORATORS = {
    "functools.lru_cache",
    "functools.cache",
    "lru_cache",
    "cache",
}
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)


def _is_jax_name(mod: Module, node: ast.AST, dotted: str) -> bool:
    resolved = mod.resolve_name(node)
    return resolved == dotted


def _jit_call(mod: Module, node: ast.AST) -> Optional[ast.Call]:
    """The ast.Call if ``node`` is a ``jax.jit(...)`` call."""
    if isinstance(node, ast.Call) and mod.resolve_call(node) == "jax.jit":
        return node
    return None


class _ModuleIndex:
    """Module-wide pass: which names are jit-made callables, which
    functions are jit factories, which self attributes hold jitted
    programs."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.jit_attrs: Set[str] = set()       # self.X = jax.jit(...)
        self.jit_globals: Set[str] = set()     # module-level X = jax.jit(..)
        self.jit_factories: Set[str] = set()   # def f(): return jax.jit(..)
        self.jitted_defs: List[Tuple[ast.FunctionDef, str]] = []
        self._index()

    def _index(self):
        mod = self.mod
        # self.X = jax.jit(...) anywhere (engine builds them in __init__)
        for node in ast.walk(mod.tree):
            for target, value in assign_targets(node) if isinstance(
                node, (ast.Assign, ast.AnnAssign, ast.AugAssign)
            ) else []:
                name = self_attr(target)
                if name and _jit_call(mod, value) is not None:
                    self.jit_attrs.add(name)

        # jit factories to a fixed point: a function whose return value
        # is a jax.jit call, a jit-assigned local, or a call to another
        # factory — tuple returns propagate elementwise
        funcs = {
            n.name: n
            for n in ast.walk(mod.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        changed = True
        while changed:
            changed = False
            for name, fn in funcs.items():
                if name in self.jit_factories:
                    continue
                if self._returns_jit(fn):
                    self.jit_factories.add(name)
                    changed = True

        # module-level jitted programs: X = jax.jit(...) (or a factory
        # call) at top level — callable from every function in the module
        for stmt in mod.tree.body:
            for target, value in assign_targets(stmt):
                if not isinstance(target, ast.Name):
                    continue
                if _jit_call(mod, value) is not None:
                    self.jit_globals.add(target.id)
                elif isinstance(value, ast.Call) and (
                    isinstance(value.func, ast.Name)
                    and value.func.id in self.jit_factories
                ):
                    self.jit_globals.add(target.id)

        # jitted function defs: def f wrapped as jax.jit(f) or @jax.jit,
        # plus lambdas/defs passed directly to jax.jit — JAX102's scope
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(
                    self.mod.resolve_name(
                        d.func if isinstance(d, ast.Call) else d
                    )
                    == "jax.jit"
                    for d in node.decorator_list
                ):
                    self.jitted_defs.append((node, node.name))
            call = _jit_call(self.mod, node)
            if call is not None and call.args:
                arg = call.args[0]
                if isinstance(arg, ast.Name) and arg.id in funcs:
                    self.jitted_defs.append((funcs[arg.id], arg.id))

    def _returns_jit(self, fn: ast.FunctionDef) -> bool:
        jit_locals: Set[str] = set()
        for stmt in ast.walk(fn):
            for target, value in assign_targets(stmt):
                if not isinstance(target, ast.Name):
                    continue
                if self._is_jit_expr(value, jit_locals):
                    jit_locals.add(target.id)
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                values = (
                    stmt.value.elts
                    if isinstance(stmt.value, ast.Tuple)
                    else [stmt.value]
                )
                if any(self._is_jit_expr(v, jit_locals) for v in values):
                    return True
        return False

    def _is_jit_expr(self, value: ast.AST, jit_locals: Set[str]) -> bool:
        if _jit_call(self.mod, value) is not None:
            return True
        if isinstance(value, ast.Name) and value.id in jit_locals:
            return True
        if isinstance(value, ast.Call):
            callee = value.func
            if isinstance(callee, ast.Name) and callee.id in (
                self.jit_factories
            ):
                return True
        return False


class _TaintWalker:
    """Per-function device-taint pass (JAX101) — two sweeps so names
    assigned late still taint uses inside earlier loop bodies."""

    def __init__(
        self,
        mod: Module,
        index: _ModuleIndex,
        fn: ast.FunctionDef,
        symbol: str,
        findings: List[Finding],
        pre_tainted: Optional[Set[str]] = None,
        rule: str = "JAX101",
    ):
        self.mod = mod
        self.index = index
        self.fn = fn
        self.symbol = symbol
        self.findings = findings
        self.rule = rule
        self.tainted: Set[str] = set(pre_tainted or ())
        self.device_fns: Set[str] = set()   # local names bound to jitted fns

    def run(self):
        self.sweep()
        self._flag_syncs()

    def sweep(self):
        """Propagate taint through the function's assignments — two
        passes so names assigned late still taint uses inside earlier
        loop bodies. Shared by JAX101 (run) and JAX102
        (_traced_branch_findings)."""
        for _ in range(2):
            for stmt in ast.walk(self.fn):
                for target, value in assign_targets(stmt):
                    self._assign(target, value)

    def _assign(self, target: ast.expr, value: ast.expr):
        if isinstance(target, ast.Name):
            if self._is_device_fn_expr(value):
                self.device_fns.add(target.id)
            elif self._is_tainted(value):
                self.tainted.add(target.id)

    def _is_device_fn_expr(self, value: ast.expr) -> bool:
        if _jit_call(self.mod, value) is not None:
            return True
        if isinstance(value, ast.Call):
            callee = value.func
            if (
                isinstance(callee, ast.Name)
                and callee.id in self.index.jit_factories
            ):
                return True
        return False

    def _is_device_call(self, call: ast.Call) -> bool:
        func = call.func
        resolved = self.mod.resolve_call(call)
        if resolved is not None:
            if resolved.startswith("jax.numpy."):
                return True
            if resolved in ("jax.device_put",):
                return True
        if isinstance(func, ast.Name) and (
            func.id in self.device_fns
            or func.id in self.index.jit_globals
        ):
            return True
        attr = self_attr(func)
        if attr is not None and attr in self.index.jit_attrs:
            return True
        return False

    def _is_tainted(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Call):
            if self._is_device_call(expr):
                return True
            # explicit sync launders: jax.block_until_ready(x) is host-safe
            if self.mod.resolve_call(expr) == "jax.block_until_ready":
                return False
            # the sync calls themselves return host values
            if self._sync_kind(expr) is not None:
                return False
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_META:
                return False
            return self._is_tainted(expr.value)
        if isinstance(expr, ast.Subscript):
            return self._is_tainted(expr.value)
        if isinstance(expr, ast.BinOp):
            return self._is_tainted(expr.left) or self._is_tainted(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self._is_tainted(expr.operand)
        if isinstance(expr, ast.Compare):
            return self._is_tainted(expr.left) or any(
                self._is_tainted(c) for c in expr.comparators
            )
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._is_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return self._is_tainted(expr.body) or self._is_tainted(
                expr.orelse
            )
        if isinstance(expr, ast.Starred):
            return self._is_tainted(expr.value)
        return False

    def _sync_kind(self, call: ast.Call) -> Optional[str]:
        """The human name of the implicit sync this call performs, or
        None. ``jax.block_until_ready`` is NOT here — it is the allowed
        explicit form."""
        func = call.func
        resolved = self.mod.resolve_call(call)
        if resolved in ("numpy.asarray", "numpy.array"):
            return resolved.replace("numpy.", "np.")
        if resolved == "jax.device_get":
            return "jax.device_get"
        if (
            isinstance(func, ast.Name)
            and func.id in _SYNC_BUILTINS
            and func.id not in self.tainted
        ):
            return f"{func.id}()"
        if isinstance(func, ast.Attribute) and func.attr == "item":
            return ".item()"
        return None

    def _flag_syncs(self):
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Call):
                continue
            kind = self._sync_kind(node)
            if kind is None:
                continue
            if kind == ".item()":
                args = [node.func.value]
            else:
                args = list(node.args)
            if any(self._is_tainted(a) for a in args):
                self.findings.append(
                    Finding(
                        self.rule,
                        "error",
                        self.mod.rel_path,
                        node.lineno,
                        self.symbol,
                        f"implicit host sync: {kind} on a device value — "
                        f"use an explicit jax.block_until_ready at a "
                        f"documented sync point",
                    )
                )


def _traced_branch_findings(
    mod: Module, index: _ModuleIndex, findings: List[Finding]
):
    """JAX102: Python control flow on traced values inside jitted defs."""
    for fn, name in index.jitted_defs:
        symbol = _symbol_for(mod, fn)
        params = {
            a.arg
            for a in (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
            )
            if a.arg != "self"
        }
        walker = _TaintWalker(
            mod, index, fn, symbol, [], pre_tainted=params
        )
        walker.sweep()
        for node in ast.walk(fn):
            test = None
            if isinstance(node, (ast.If, ast.While)):
                test = node.test
            elif isinstance(node, ast.IfExp):
                test = node.test
            elif isinstance(node, ast.Assert):
                test = node.test
            if test is not None and walker._is_tainted(test):
                findings.append(
                    Finding(
                        "JAX102",
                        "error",
                        mod.rel_path,
                        node.lineno,
                        symbol,
                        f"Python branch on a traced value inside jitted "
                        f"function {name!r} — fails at trace time or "
                        f"forces retraces; use lax.cond/select",
                    )
                )


def _static_arg_findings(
    mod: Module, index: _ModuleIndex, findings: List[Finding]
):
    """JAX103: mutable literals at static jit parameters. Resolved for
    jit calls that name their function and are assigned to a local/attr
    that is then called in the same module."""
    static_of: Dict[str, Tuple[Set[int], Set[str]]] = {}
    # find assignments `f = jax.jit(..., static_...)` then calls `f(...)`
    for stmt in ast.walk(mod.tree):
        for target, value in assign_targets(stmt):
            call = _jit_call(mod, value)
            if call is None:
                continue
            nums, names = set(), set()
            for kw in call.keywords:
                if kw.arg == "static_argnums":
                    nums |= _int_elts(kw.value)
                elif kw.arg == "static_argnames":
                    names |= _str_elts(kw.value)
            if not nums and not names:
                continue
            tname = (
                target.id
                if isinstance(target, ast.Name)
                else self_attr(target)
            )
            if tname:
                static_of[tname] = (nums, names)
    if not static_of:
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = (
            func.id if isinstance(func, ast.Name) else self_attr(func)
        )
        if fname not in static_of:
            continue
        nums, names = static_of[fname]
        for i, arg in enumerate(node.args):
            if i in nums and isinstance(arg, _MUTABLE_LITERALS):
                findings.append(_static_finding(mod, node, fname, f"#{i}"))
        for kw in node.keywords:
            if kw.arg in names and isinstance(kw.value, _MUTABLE_LITERALS):
                findings.append(
                    _static_finding(mod, node, fname, kw.arg or "?")
                )


def _static_finding(mod, node, fname, which) -> Finding:
    return Finding(
        "JAX103",
        "error",
        mod.rel_path,
        node.lineno,
        _symbol_for(mod, node),
        f"mutable literal passed for static jit argument {which} of "
        f"{fname!r} — static args must be hashable (use a tuple)",
    )


def _jit_in_function_findings(
    mod: Module, findings: List[Finding]
):
    """JAX104: jax.jit invoked inside a function body without
    memoization — every call re-traces a fresh closure."""

    def walk(body, owner: Optional[str], memoized: bool, cls: Optional[str]):
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk(node.body, None, False, node.name)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                memo = memoized or bool(
                    set(decorator_names(node, mod)) & _MEMO_DECORATORS
                )
                # __init__ builds the programs once per object: setup,
                # not per-call tracing
                allowed = memo or node.name == "__init__"
                name = f"{cls}.{node.name}" if cls else node.name
                walk(node.body, name if not allowed else None, allowed, cls)
                continue
            for sub in ast.walk(node):
                call = _jit_call(mod, sub)
                if call is not None and owner is not None and not memoized:
                    findings.append(
                        Finding(
                            "JAX104",
                            "error",
                            mod.rel_path,
                            sub.lineno,
                            owner,
                            f"jax.jit called inside {owner!r} without "
                            f"lru_cache memoization — every call traces "
                            f"a fresh closure (retrace hazard); cache "
                            f"the jitted program",
                        )
                    )

    walk(mod.tree.body, None, False, None)


def _donated_reuse_findings(
    mod: Module, findings: List[Finding]
):
    """JAX105: host reuse of a donated buffer after the donating call.

    Donating callables are assignments ``X = jax.jit(...,
    donate_argnums=(..))`` (local, module-level, or ``self.X``). At each
    call site ``X(a, b, ...)``, a plain-Name argument in a donated
    position marks that name dead from the call's last line onward —
    unless the SAME statement rebinds it from the call's results (the
    blessed carried-state pattern). Any later Load of a dead name flags,
    up to and including the right-hand side of a later independent
    rebind (``state = other(state)`` still reads the deleted array);
    Loads strictly after a rebind are laundered."""
    donating: Dict[str, Set[int]] = {}
    for stmt in ast.walk(mod.tree):
        for _target, value in assign_targets(stmt) if isinstance(
            stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)
        ) else []:
            call = _jit_call(mod, value)
            if call is None:
                continue
            nums: Set[int] = set()
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    nums |= _int_elts(kw.value)
            if not nums:
                continue
            tname = (
                _target.id
                if isinstance(_target, ast.Name)
                else self_attr(_target)
            )
            if tname:
                donating[tname] = donating.get(tname, set()) | nums
    if not donating:
        return

    def scan(fn: ast.FunctionDef, symbol: str) -> None:
        donations: List[Tuple[str, int, str]] = []  # name, end line, fn
        assigns: List[Tuple[str, int]] = []
        for stmt in ast.walk(fn):
            if not isinstance(stmt, ast.stmt):
                continue
            rebound: Set[str] = set()
            for target, _value in assign_targets(stmt):
                if isinstance(target, ast.Name):
                    rebound.add(target.id)
                    assigns.append((target.id, stmt.lineno))
            # scan only THIS statement's own expressions — a compound
            # statement (if/try/for/with) must not re-visit its children
            # with an empty rebound set (they are statements of their
            # own and get their own visit)
            exprs: List[ast.expr] = []
            for _field, value in ast.iter_fields(stmt):
                if isinstance(value, ast.expr):
                    exprs.append(value)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.expr):
                            exprs.append(v)
                        elif isinstance(v, ast.withitem):
                            exprs.append(v.context_expr)
            for sub in (
                node for e in exprs for node in ast.walk(e)
            ):
                if not isinstance(sub, ast.Call):
                    continue
                func = sub.func
                fname = (
                    func.id
                    if isinstance(func, ast.Name)
                    else self_attr(func)
                )
                if fname not in donating:
                    continue
                dend = getattr(sub, "end_lineno", None) or sub.lineno
                for i, arg in enumerate(sub.args):
                    if (
                        i in donating[fname]
                        and isinstance(arg, ast.Name)
                        and arg.id not in rebound
                    ):
                        donations.append((arg.id, dend, fname))
        for name, dend, fname in donations:
            rebinds_after = [
                line for n, line in assigns if n == name and line > dend
            ]
            clear_at = min(rebinds_after) if rebinds_after else None
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id == name
                    and node.lineno > dend
                    and (clear_at is None or node.lineno <= clear_at)
                ):
                    findings.append(
                        Finding(
                            "JAX105",
                            "error",
                            mod.rel_path,
                            node.lineno,
                            symbol,
                            f"use of {name!r} after it was donated to "
                            f"{fname!r} (donate_argnums) — the buffer "
                            f"is deleted at dispatch; rebind the name "
                            f"from the call's results or rebuild the "
                            f"state",
                        )
                    )
                    break  # one finding per donation is signal enough

    seen: Set[int] = set()
    for cls in mod.classes():
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                seen.add(id(node))
                scan(node, f"{cls.name}.{node.name}")
    for fn in mod.functions():
        if id(fn) not in seen:
            scan(fn, fn.name)


def _symbol_for(mod: Module, node: ast.AST) -> str:
    """Qualname-ish symbol of the enclosing class.method/function."""
    target_line = getattr(node, "lineno", 0)
    best = "<module>"
    for n in ast.walk(mod.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if n.lineno <= target_line <= max(
                getattr(n, "end_lineno", n.lineno), n.lineno
            ):
                # prefer the innermost enclosing def — walk order is
                # outer-first, so keep overwriting
                best = _qual_in_classes(mod, n)
    return best


def _qual_in_classes(mod: Module, fn: ast.FunctionDef) -> str:
    for cls in mod.classes():
        for n in ast.walk(cls):
            if n is fn:
                return f"{cls.name}.{fn.name}"
    return fn.name


def _int_elts(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
    return out


def _str_elts(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
    return out


def analyze_module(mod: Module) -> List[Finding]:
    """All JAX-hygiene rules over one serving-path module."""
    findings: List[Finding] = []
    index = _ModuleIndex(mod)

    # JAX101 per function (methods and plain defs, nested included once
    # as part of their outermost def's walk — ast.walk covers them; run
    # per top-level def so symbols attribute correctly)
    seen: Set[int] = set()
    for cls in mod.classes():
        for name, fn in (
            (n.name, n)
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ):
            seen.add(id(fn))
            _TaintWalker(
                mod, index, fn, f"{cls.name}.{name}", findings
            ).run()
    for fn in mod.functions():
        if id(fn) not in seen:
            _TaintWalker(mod, index, fn, fn.name, findings).run()

    _traced_branch_findings(mod, index, findings)
    _static_arg_findings(mod, index, findings)
    _jit_in_function_findings(mod, findings)
    _donated_reuse_findings(mod, findings)
    return findings
