"""Lock-discipline analyzer (LOCK1xx).

Per class, from ``__init__``-style assignments, the analyzer types every
``self.X`` attribute that matters to concurrency — ``threading.Lock`` /
``RLock`` / ``Condition(lock)`` / ``Event``, ``queue.Queue`` (bounded vs
unbounded), sockets, threads — then walks each method tracking the
ordered set of locks held through ``with self.X:`` nesting, with a
transitive pass over intra-class ``self.method()`` calls.

Rules:

  LOCK101 (error)   lock-order cycle: two with-nestings acquire the same
                    pair of locks in opposite orders somewhere in the
                    class — the classic ABBA deadlock.
  LOCK102 (error)   blocking call while holding a lock: ``Future.result``,
                    ``Queue.get`` (always) / ``put`` (bounded queues),
                    socket I/O, ``Thread.join``, ``Event.wait``,
                    ``time.sleep``, ``jax.block_until_ready`` /
                    ``jax.device_get`` — directly in a with-region or via
                    an intra-class call chain. A ``Condition.wait`` on a
                    HELD lock is exempt locally (waiting releases that
                    lock) but still blocks any OTHER lock a caller holds,
                    and propagates as such.
  LOCK103 (warning) guarded-attribute violation: an attribute written
                    under the class lock at one site and with no lock at
                    another (``__init__`` excluded). Private helpers
                    inherit the locks every intra-class call site is
                    guaranteed to hold, so ``*_locked``-style helpers
                    don't false-positive.
  LOCK104 (error)   self-deadlock: a non-reentrant lock (re-)acquired —
                    directly or through a call chain — while already held.
  LOCK105 (error)   ``Condition.wait`` while holding a DIFFERENT lock
                    than the condition's own: the wait releases only its
                    own lock, so everything else stays held for the full
                    sleep.
  LOCK106 (error)   CROSS-CLASS lock-order cycle (:func:`analyze_cross`,
                    over the shared call graph): class A calls into
                    class B while holding an A-lock and B's method
                    (transitively) acquires a B-lock, while some B
                    method does the reverse — the coalescer↔engine↔
                    admission interleaving per-class analysis cannot
                    see. Calls are matched to call-graph edges by
                    (line, name), so only resolvable package methods
                    participate.

Scope limits (deliberate, documented): attribute-level tracking only
(lock objects passed around in locals are not followed), per-class
rules use intra-class call graphs only (cross-object calls are the
LOCK106 pass's job, and that pass follows one cross-class hop from a
held region into the callee's transitive intra-class acquisitions), and
nested ``def``s are analyzed with the locks held at their definition
site (a closure defined under a lock is almost always called under it
in this codebase's dispatcher/handler idiom).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from ._astutil import (
    Module,
    assign_targets,
    call_kw,
    methods_of,
    self_attr,
)
from .findings import Finding

# attribute kinds
LOCK, RLOCK, CONDITION, EVENT, QUEUE, SOCKET, THREAD = range(7)

_LOCK_CTORS = {
    "threading.Lock": LOCK,
    "threading.RLock": RLOCK,
    "threading.Condition": CONDITION,
    "threading.Event": EVENT,
    "queue.Queue": QUEUE,
    "queue.LifoQueue": QUEUE,
    "queue.PriorityQueue": QUEUE,
    "socket.socket": SOCKET,
    "socket.create_server": SOCKET,
    "socket.create_connection": SOCKET,
    "threading.Thread": THREAD,
}

_SOCKET_BLOCKING = {
    "accept",
    "connect",
    "recv",
    "recv_into",
    "recvfrom",
    "send",
    "sendall",
    "sendto",
}

_GLOBAL_BLOCKING = {
    "time.sleep": "time.sleep",
    "socket.create_connection": "socket.create_connection",
    "jax.block_until_ready": "jax.block_until_ready (device sync)",
    "jax.device_get": "jax.device_get (device fetch)",
}


@dataclasses.dataclass
class _Attr:
    kind: int
    bounded: bool = False          # queues: maxsize given and non-zero
    cond_lock: Optional[str] = None  # conditions: underlying lock attr


@dataclasses.dataclass
class _Block:
    op: str
    line: int
    held: Tuple[str, ...]
    # Condition.wait on lock L releases L while sleeping: it only blocks
    # a caller's OTHER locks. None for ops that block unconditionally.
    releases: Optional[str] = None


@dataclasses.dataclass
class _MethodInfo:
    name: str
    acquires: Set[str] = dataclasses.field(default_factory=set)
    edges: List[Tuple[str, str, int]] = dataclasses.field(default_factory=list)
    blocks: List[_Block] = dataclasses.field(default_factory=list)
    self_calls: List[Tuple[str, Tuple[str, ...], int]] = dataclasses.field(
        default_factory=list
    )
    # attr calls on OTHER objects made while holding locks, for the
    # cross-class pass: (method name, held locks, line)
    ext_calls: List[Tuple[str, Tuple[str, ...], int]] = dataclasses.field(
        default_factory=list
    )
    writes: List[Tuple[str, FrozenSet[str], int]] = dataclasses.field(
        default_factory=list
    )
    findings: List[Finding] = dataclasses.field(default_factory=list)


def _collect_attr_types(
    mod: Module, methods: Dict[str, ast.FunctionDef]
) -> Dict[str, _Attr]:
    """Type self.X attributes from constructor-call assignments anywhere
    in the class (lazily-created locks/threads included)."""
    attrs: Dict[str, _Attr] = {}
    for fn in methods.values():
        for stmt in ast.walk(fn):
            for target, value in assign_targets(stmt):
                name = self_attr(target)
                if name is None or not isinstance(value, ast.Call):
                    continue
                callee = mod.resolve_call(value)
                kind = _LOCK_CTORS.get(callee or "")
                if kind is None:
                    continue
                attr = _Attr(kind)
                if kind == QUEUE:
                    size = (
                        value.args[0]
                        if value.args
                        else call_kw(value, "maxsize")
                    )
                    attr.bounded = size is not None and not (
                        isinstance(size, ast.Constant)
                        and size.value in (0, None)
                    )
                elif kind == CONDITION:
                    arg = value.args[0] if value.args else None
                    attr.cond_lock = (
                        self_attr(arg) if arg is not None else name
                    )
                attrs[name] = attr
    return attrs


def _lock_identity(name: str, attrs: Dict[str, _Attr]) -> Optional[str]:
    """The lock an acquisition of self.<name> actually holds: conditions
    alias their underlying lock."""
    attr = attrs.get(name)
    if attr is None:
        return None
    if attr.kind in (LOCK, RLOCK):
        return name
    if attr.kind == CONDITION:
        return attr.cond_lock or name
    return None


def _is_reentrant(name: str, attrs: Dict[str, _Attr]) -> bool:
    """Conservative reentrancy check that tolerates UNKNOWN locks: a
    Condition can wrap an attribute the typing pass never saw assigned
    from a recognized constructor (e.g. a lock injected as an __init__
    parameter) — such a lock must analyze as plain/non-reentrant, not
    crash the gate with a KeyError."""
    attr = attrs.get(name)
    return attr is not None and attr.kind == RLOCK


class _MethodWalker:
    """One method's local pass: held-lock tracking + site collection."""

    def __init__(
        self,
        mod: Module,
        cls_name: str,
        attrs: Dict[str, _Attr],
        method_names: Set[str],
        info: _MethodInfo,
        rel_path: str,
    ):
        self.mod = mod
        self.cls_name = cls_name
        self.attrs = attrs
        self.method_names = method_names
        self.info = info
        self.rel_path = rel_path
        self.symbol = f"{cls_name}.{info.name}"

    def _finding(self, rule: str, severity: str, line: int, msg: str):
        self.info.findings.append(
            Finding(rule, severity, self.rel_path, line, self.symbol, msg)
        )

    # -- statement walk ----------------------------------------------------
    def walk_body(self, body: List[ast.stmt], held: Tuple[str, ...]):
        for stmt in body:
            self.walk_stmt(stmt, held)

    def walk_stmt(self, stmt: ast.stmt, held: Tuple[str, ...]):
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                self._scan_expr(item.context_expr, new_held)
                name = self_attr(item.context_expr)
                lock = _lock_identity(name, self.attrs) if name else None
                if lock is not None:
                    new_held = self._acquire(
                        lock, new_held, stmt.lineno
                    )
            self.walk_body(stmt.body, new_held)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: analyzed with the defining site's locks (see
            # module docstring); decorators/defaults scanned too
            for dec in stmt.decorator_list:
                self._scan_expr(dec, held)
            self.walk_body(stmt.body, held)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        # writes
        for target, _value in assign_targets(stmt):
            attr = self._written_attr(target)
            if attr is not None:
                self.info.writes.append(
                    (attr, frozenset(held), stmt.lineno)
                )
        # expressions in this statement (excluding nested-stmt bodies)
        for expr in self._stmt_exprs(stmt):
            self._scan_expr(expr, held)
        # recurse into compound bodies
        for field in ("body", "orelse", "finalbody"):
            body = getattr(stmt, field, None)
            if body:
                self.walk_body(body, held)
        for handler in getattr(stmt, "handlers", []) or []:
            self.walk_body(handler.body, held)

    @staticmethod
    def _stmt_exprs(stmt: ast.stmt) -> List[ast.expr]:
        out = []
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            if isinstance(value, ast.expr):
                out.append(value)
            elif isinstance(value, list):
                out.extend(v for v in value if isinstance(v, ast.expr))
        return out

    @staticmethod
    def _written_attr(target: ast.expr) -> Optional[str]:
        # self.X = ..., self.X[...] = ..., self.X.Y = ... all count as
        # writes into X's guarded state
        node = target
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            name = self_attr(node)
            if name is not None:
                return name
            node = node.value
        return None

    # -- acquisition -------------------------------------------------------
    def _acquire(
        self, lock: str, held: Tuple[str, ...], line: int
    ) -> Tuple[str, ...]:
        if lock in held and not _is_reentrant(lock, self.attrs):
            self._finding(
                "LOCK104",
                "error",
                line,
                f"non-reentrant lock self.{lock} re-acquired while "
                f"already held — self-deadlock",
            )
            return held
        for h in held:
            if h != lock:
                self.info.edges.append((h, lock, line))
        self.info.acquires.add(lock)
        return held + (lock,) if lock not in held else held

    # -- expression scan ---------------------------------------------------
    def _scan_expr(self, expr: ast.expr, held: Tuple[str, ...]):
        # hand-rolled walk that PRUNES lambda subtrees (ast.walk would
        # descend into them): a lambda merely defined under a lock runs
        # at an unknown later time, so its body's calls must not inherit
        # the held set (deferred-callback idiom)
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Call):
                self._scan_call(node, held)
            stack.extend(ast.iter_child_nodes(node))

    def _scan_call(self, call: ast.Call, held: Tuple[str, ...]):
        func = call.func
        resolved = self.mod.resolve_call(call)
        if resolved in _GLOBAL_BLOCKING:
            self._blocking(_GLOBAL_BLOCKING[resolved], call.lineno, held)
            return
        if not isinstance(func, ast.Attribute):
            return
        method = func.attr
        recv_attr = self_attr(func.value)
        if recv_attr is not None:
            attr = self.attrs.get(recv_attr)
            if attr is not None:
                self._scan_typed_attr_call(
                    recv_attr, attr, method, call, held
                )
                return
        # self.method(...) intra-class call
        if (
            isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and method in self.method_names
        ):
            self.info.self_calls.append((method, held, call.lineno))
            return
        # anything else reached under a lock is a candidate cross-class
        # call: analyze_cross resolves it against the call graph
        if held:
            self.info.ext_calls.append((method, held, call.lineno))
        # Future.result() on any receiver: .result( is unambiguous in
        # this codebase (concurrent.futures) and blocks until completion
        if method == "result":
            self._blocking("Future.result()", call.lineno, held)

    def _scan_typed_attr_call(
        self,
        name: str,
        attr: _Attr,
        method: str,
        call: ast.Call,
        held: Tuple[str, ...],
    ):
        line = call.lineno
        if attr.kind == QUEUE:
            if method == "get" or (method == "put" and attr.bounded):
                kind = "bounded " if attr.bounded and method == "put" else ""
                self._blocking(
                    f"{kind}queue self.{name}.{method}()", line, held
                )
            elif method == "join":
                self._blocking(f"queue self.{name}.join()", line, held)
        elif attr.kind == SOCKET:
            if method in _SOCKET_BLOCKING:
                self._blocking(f"socket self.{name}.{method}()", line, held)
        elif attr.kind == THREAD:
            if method == "join":
                self._blocking(f"thread self.{name}.join()", line, held)
        elif attr.kind == EVENT:
            if method == "wait":
                self._blocking(f"event self.{name}.wait()", line, held)
        elif attr.kind == CONDITION:
            if method in ("wait", "wait_for"):
                underlying = attr.cond_lock or name
                others = [h for h in held if h != underlying]
                if held and underlying not in held:
                    self._finding(
                        "LOCK105",
                        "error",
                        line,
                        f"self.{name}.{method}() waits on "
                        f"self.{underlying} while holding "
                        f"{_fmt(held)} — the wait releases only its own "
                        f"lock",
                    )
                elif others:
                    self._finding(
                        "LOCK102",
                        "error",
                        line,
                        f"self.{name}.{method}() releases only "
                        f"self.{underlying}; {_fmt(tuple(others))} "
                        f"stays held for the whole wait",
                    )
                # always record for transitive propagation: callers
                # holding other locks block here
                self.info.blocks.append(
                    _Block(
                        f"Condition self.{name}.{method}()",
                        line,
                        held,
                        releases=underlying,
                    )
                )
        elif attr.kind in (LOCK, RLOCK):
            if method == "acquire":
                lock = _lock_identity(name, self.attrs)
                if lock:
                    self._acquire(lock, held, line)

    def _blocking(self, op: str, line: int, held: Tuple[str, ...]):
        self.info.blocks.append(_Block(op, line, held))
        if held:
            self._finding(
                "LOCK102",
                "error",
                line,
                f"blocking {op} while holding {_fmt(held)}",
            )


def _fmt(locks: Tuple[str, ...]) -> str:
    return ", ".join(f"self.{name}" for name in locks)


def _transitive(
    infos: Dict[str, _MethodInfo], attrs: Dict[str, _Attr], cls: str, path: str
) -> List[Finding]:
    """Propagate blocking ops and acquisitions across intra-class calls,
    then detect lock-order cycles."""
    findings: List[Finding] = []
    # transitive blocking sets: op description per method (first site)
    blocks: Dict[str, Dict[str, Optional[str]]] = {
        m: {b.op: b.releases for b in info.blocks}
        for m, info in infos.items()
    }
    acquires: Dict[str, Set[str]] = {
        m: set(info.acquires) for m, info in infos.items()
    }
    changed = True
    while changed:
        changed = False
        for m, info in infos.items():
            for callee, _held, _line in info.self_calls:
                for op, releases in blocks.get(callee, {}).items():
                    if op not in blocks[m]:
                        blocks[m][op] = releases
                        changed = True
                extra = acquires.get(callee, set()) - acquires[m]
                if extra:
                    acquires[m] |= extra
                    changed = True

    edges: List[Tuple[str, str, int, str]] = []
    for m, info in infos.items():
        for a, b, line in info.edges:
            edges.append((a, b, line, m))
        for callee, held, line in info.self_calls:
            if not held:
                continue
            symbol = f"{cls}.{m}"
            # blocking through the call chain
            blocking_ops = [
                op
                for op, releases in blocks.get(callee, {}).items()
                if releases is None
                or any(h != releases for h in held)
            ]
            if blocking_ops:
                findings.append(
                    Finding(
                        "LOCK102",
                        "error",
                        path,
                        line,
                        symbol,
                        f"call to self.{callee}() blocks "
                        f"({'; '.join(sorted(blocking_ops))}) while "
                        f"holding {_fmt(held)}",
                    )
                )
            # acquisition through the call chain
            for lock in sorted(acquires.get(callee, set())):
                if lock in held and not _is_reentrant(lock, attrs):
                    findings.append(
                        Finding(
                            "LOCK104",
                            "error",
                            path,
                            line,
                            symbol,
                            f"call to self.{callee}() re-acquires held "
                            f"non-reentrant lock self.{lock} — "
                            f"self-deadlock",
                        )
                    )
                else:
                    for h in held:
                        if h != lock:
                            edges.append((h, lock, line, m))

    # cycle detection over the acquisition-order graph
    graph: Dict[str, Dict[str, Tuple[int, str]]] = {}
    for a, b, line, m in edges:
        graph.setdefault(a, {}).setdefault(b, (line, m))
    reported: Set[FrozenSet[str]] = set()
    for a in sorted(graph):
        for b in sorted(graph[a]):
            if a in graph.get(b, {}) and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                line, m = graph[a][b]
                line2, m2 = graph[b][a]
                findings.append(
                    Finding(
                        "LOCK101",
                        "error",
                        path,
                        line,
                        f"{cls}.{m}",
                        f"lock-order cycle: self.{a} → self.{b} here, "
                        f"but self.{b} → self.{a} in {cls}.{m2} "
                        f"(line {line2}) — ABBA deadlock",
                    )
                )
    return findings


def _guarded_attr_findings(
    infos: Dict[str, _MethodInfo], cls: str, path: str
) -> List[Finding]:
    """LOCK103: writes both under a lock and bare. Private helpers get
    the locks EVERY intra-class call site guarantees (fixed point), so
    hold-the-lock helpers don't read as bare writers."""
    all_locks: Set[str] = set()
    for info in infos.values():
        for _a, held, _l in info.writes:
            all_locks |= held
        all_locks |= info.acquires
    # guaranteed entry locks per method
    callers: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
    for m, info in infos.items():
        for callee, held, _line in info.self_calls:
            callers.setdefault(callee, []).append((m, held))
    entry: Dict[str, FrozenSet[str]] = {}
    for m in infos:
        is_private = m.startswith("_") and not m.startswith("__")
        entry[m] = (
            frozenset(all_locks)
            if is_private and callers.get(m)
            else frozenset()
        )
    for _ in range(len(infos) + 1):
        changed = False
        for m in infos:
            if not callers.get(m) or entry[m] == frozenset():
                continue
            new = frozenset(all_locks)
            for caller, held in callers[m]:
                new &= frozenset(held) | entry[caller]
            if new != entry[m]:
                entry[m] = new
                changed = True
        if not changed:
            break

    # collect effective write contexts
    locked: Dict[str, Tuple[str, int, str]] = {}   # attr -> lock, line, m
    bare: Dict[str, Tuple[int, str]] = {}          # attr -> line, m
    for m, info in infos.items():
        if m == "__init__":
            continue
        for attr, held, line in info.writes:
            eff = held | entry[m]
            if eff:
                locked.setdefault(attr, (sorted(eff)[0], line, m))
            else:
                bare.setdefault(attr, (line, m))
    findings = []
    for attr in sorted(set(locked) & set(bare)):
        lock, lline, lm = locked[attr]
        bline, bm = bare[attr]
        findings.append(
            Finding(
                "LOCK103",
                "warning",
                path,
                bline,
                f"{cls}.{bm}",
                f"self.{attr} written without a lock here but under "
                f"self.{lock} in {cls}.{lm} (line {lline}) — guarded "
                f"attribute mutated outside its lock",
            )
        )
    return findings


def _class_pass(
    mod: Module, cls: ast.ClassDef
) -> Optional[Tuple[Dict[str, _Attr], Dict[str, _MethodInfo]]]:
    """Walk one class's methods; None when the class holds no locks."""
    methods = methods_of(cls)
    attrs = _collect_attr_types(mod, methods)
    if not any(
        a.kind in (LOCK, RLOCK, CONDITION) for a in attrs.values()
    ):
        return None  # lock-free class: nothing to check
    infos: Dict[str, _MethodInfo] = {}
    for name, fn in methods.items():
        info = _MethodInfo(name)
        walker = _MethodWalker(
            mod, cls.name, attrs, set(methods), info, mod.rel_path
        )
        walker.walk_body(fn.body, ())
        infos[name] = info
    return attrs, infos


def analyze_module(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    for cls in mod.classes():
        passed = _class_pass(mod, cls)
        if passed is None:
            continue
        attrs, infos = passed
        for info in infos.values():
            findings.extend(info.findings)
        findings.extend(_transitive(infos, attrs, cls.name, mod.rel_path))
        findings.extend(_guarded_attr_findings(infos, cls.name, mod.rel_path))
    return findings


def _transitive_acquires(
    infos: Dict[str, _MethodInfo]
) -> Dict[str, Set[str]]:
    """Fixed point of each method's acquired locks through intra-class
    calls (the cross-class pass needs what a callee EVENTUALLY locks)."""
    acq = {m: set(info.acquires) for m, info in infos.items()}
    changed = True
    while changed:
        changed = False
        for m, info in infos.items():
            for callee, _held, _line in info.self_calls:
                extra = acq.get(callee, set()) - acq[m]
                if extra:
                    acq[m] |= extra
                    changed = True
    return acq


def analyze_cross(modules: Sequence[Module], graph) -> List[Finding]:
    """LOCK106: lock-order cycles ACROSS classes, along call-graph edges.

    Per-class analysis sees ``with self._lock: self.engine.admit(...)``
    as an opaque external call. Here every such held-region call is
    matched (by line + method name) to its resolved call-graph edges;
    when the callee is a method of ANOTHER lock-holding class, the
    caller's held locks order before everything the callee transitively
    acquires. Opposite-direction edge pairs over these class-qualified
    locks are the coalescer↔engine↔admission ABBA deadlocks that are
    invisible per-class.
    """
    # one _class_pass per lock class, keyed like call-graph nodes
    per_class: Dict[Tuple[str, str], Tuple[Dict[str, _Attr], Dict[str, _MethodInfo]]] = {}
    for mod in modules:
        for cls in mod.classes():
            passed = _class_pass(mod, cls)
            if passed is not None:
                per_class[(mod.rel_path, cls.name)] = passed
    acq_of = {
        key: _transitive_acquires(infos)
        for key, (_attrs, infos) in per_class.items()
    }

    # call-graph edges indexed by (caller key, line, callee name)
    edge_map: Dict[Tuple[str, int, str], List[str]] = {}
    for key, sites in graph.edges.items():
        for target, site in sites:
            edge_map.setdefault((key, site.line, site.name), []).append(
                target
            )

    # cross edges over class-qualified locks: "Cls.lockattr"
    cross: Dict[Tuple[str, str], Tuple[str, str, int]] = {}
    for (rel_path, cls_name), (_attrs, infos) in per_class.items():
        for m, info in infos.items():
            caller_key = f"{rel_path}::{cls_name}.{m}"
            for name, held, line in info.ext_calls:
                for target in edge_map.get((caller_key, line, name), ()):
                    node = graph.nodes.get(target)
                    if node is None or node.cls_name is None:
                        continue
                    callee_cls = (node.mod.rel_path, node.cls_name)
                    if callee_cls == (rel_path, cls_name):
                        continue  # intra-class: LOCK101's job
                    callee_acq = acq_of.get(callee_cls, {}).get(
                        node.fn.name, set()
                    )
                    for la in held:
                        qa = f"{cls_name}.{la}"
                        for lb in sorted(callee_acq):
                            qb = f"{node.cls_name}.{lb}"
                            cross.setdefault(
                                (qa, qb),
                                (rel_path, f"{cls_name}.{m}", line),
                            )

    findings: List[Finding] = []
    reported: Set[FrozenSet[str]] = set()
    for (qa, qb) in sorted(cross):
        pair = frozenset((qa, qb))
        if (qb, qa) not in cross or pair in reported:
            continue
        reported.add(pair)
        path, symbol, line = cross[(qa, qb)]
        path2, symbol2, line2 = cross[(qb, qa)]
        findings.append(
            Finding(
                "LOCK106",
                "error",
                path,
                line,
                symbol,
                f"cross-class lock-order cycle: {qa} held while "
                f"calling into code that acquires {qb} here, but "
                f"{symbol2} ({path2}:{line2}) holds {qb} while "
                f"reaching {qa} — ABBA deadlock across classes",
            )
        )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
