"""Analyzer orchestration: configuration, module discovery, one entry
point shared by the CLI (``__main__``) and the tier-1 test suite."""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from . import jax_hygiene, locks, wire_schema
from ._astutil import Module, iter_modules
from .findings import Finding

_PKG_DIR = Path(__file__).resolve().parent.parent   # the package
_REPO_ROOT = _PKG_DIR.parent                        # its checkout


@dataclasses.dataclass
class Config:
    """What to analyze. Defaults describe THIS repo; tests point the
    fields at fixture trees."""

    # repo root: findings are reported relative to it
    root: Path = _REPO_ROOT
    # package tree the lock analyzer sweeps (every class with a lock)
    package: Path = _PKG_DIR
    # serving-path scope for the JAX-hygiene rules, relative to package
    serving: Tuple[str, ...] = ("engine.py", "parallel")
    # wire producer + consumer modules, relative to package
    wire_producer: str = "net/wire.py"
    wire_consumers: Tuple[str, ...] = (
        "net/node.py",
        "net/membership.py",
        "net/stats.py",
        # the answer cache's gossip handlers (cache_get/cache_answer +
        # the hotset piggyback) consume wire dicts too — ISSUE 13
        "cache/gossip.py",
    )
    # baseline file (None = no suppression)
    baseline: Optional[Path] = _PKG_DIR / "analysis" / "baseline.toml"
    # which analyzers to run
    analyzers: Tuple[str, ...] = ("locks", "jax", "wire")


def default_config() -> Config:
    return Config()


def _is_serving(rel_to_pkg: str, serving: Sequence[str]) -> bool:
    for entry in serving:
        if rel_to_pkg == entry or rel_to_pkg.startswith(
            entry.rstrip("/") + "/"
        ):
            return True
    return False


def run_analyzers(config: Optional[Config] = None) -> List[Finding]:
    """Run the configured analyzers; returns RAW findings (baseline not
    applied — callers use ``load_baseline``/``apply_baseline``, or the
    CLI which does it for them)."""
    cfg = config or default_config()
    findings: List[Finding] = []

    modules = list(iter_modules(cfg.package, cfg.root))
    by_rel_pkg = {
        m.path.relative_to(cfg.package).as_posix(): m for m in modules
    }

    if "locks" in cfg.analyzers:
        for mod in modules:
            findings.extend(locks.analyze_module(mod))

    if "jax" in cfg.analyzers:
        for rel, mod in by_rel_pkg.items():
            if _is_serving(rel, cfg.serving):
                findings.extend(jax_hygiene.analyze_module(mod))

    if "wire" in cfg.analyzers:
        producer = by_rel_pkg.get(cfg.wire_producer)
        if producer is None:
            producer_path = cfg.package / cfg.wire_producer
            if producer_path.exists():
                producer = Module(
                    producer_path,
                    producer_path.relative_to(cfg.root).as_posix(),
                )
        consumers = [
            by_rel_pkg[c] for c in cfg.wire_consumers if c in by_rel_pkg
        ]
        if producer is not None and consumers:
            findings.extend(wire_schema.analyze(producer, consumers))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
