"""Analyzer orchestration: configuration, module discovery, one entry
point shared by the CLI (``__main__``) and the tier-1 test suite.

The package tree is parsed exactly once (``iter_modules``) and the
inter-procedural call graph (analysis/callgraph.py) is built exactly
once; every analyzer that needs cross-function reachability — seams,
thread-context, cross-class lock order, wire-consumer discovery —
shares both. That is what keeps the whole gate inside its ~2 s budget
(asserted in tests/test_analysis.py).
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from . import callgraph, jax_hygiene, locks, seams, threadctx, wire_schema
from ._astutil import Module, iter_modules
from .findings import Finding

_PKG_DIR = Path(__file__).resolve().parent.parent   # the package
_REPO_ROOT = _PKG_DIR.parent                        # its checkout


@dataclasses.dataclass
class Config:
    """What to analyze. Defaults describe THIS repo; tests point the
    fields at fixture trees."""

    # repo root: findings are reported relative to it
    root: Path = _REPO_ROOT
    # package tree the lock analyzer sweeps (every class with a lock)
    package: Path = _PKG_DIR
    # serving-path scope for the JAX-hygiene rules, relative to package
    serving: Tuple[str, ...] = ("engine.py", "parallel")
    # wire producer module, relative to package
    wire_producer: str = "net/wire.py"
    # wire consumer modules, relative to package. None (the default)
    # AUTO-DISCOVERS them from the call graph: every module with a
    # ``msg``-param function reachable from a ``decode_msg`` call site.
    # The hand-maintained tuple this replaces went stale in PR 13
    # (cache/gossip.py had to be added manually); an explicit tuple is
    # still honored for fixture trees.
    wire_consumers: Optional[Tuple[str, ...]] = None
    # baseline file (None = no suppression)
    baseline: Optional[Path] = _PKG_DIR / "analysis" / "baseline.toml"
    # which analyzers to run
    analyzers: Tuple[str, ...] = ("locks", "jax", "wire", "seams", "thread")
    # dispatch shapes for the seam analyzer (None = the repo registry,
    # which silently no-ops on fixture trees; tests pass ShapeSpecs)
    shapes: Optional[Sequence[seams.ShapeSpec]] = None


@dataclasses.dataclass
class AnalysisResult:
    """Everything one analysis run produced: raw findings (baseline NOT
    applied), the five-shape contract matrix (empty dict unless the
    seam analyzer ran), the wire-consumer modules actually analyzed
    (relative to the package), and wall time."""

    findings: List[Finding]
    contract_matrix: Dict
    wire_consumers: Tuple[str, ...]
    wall_s: float


def default_config() -> Config:
    return Config()


def _is_serving(rel_to_pkg: str, serving: Sequence[str]) -> bool:
    for entry in serving:
        if rel_to_pkg == entry or rel_to_pkg.startswith(
            entry.rstrip("/") + "/"
        ):
            return True
    return False


def discover_wire_consumers(
    graph: callgraph.CallGraph,
    by_rel_pkg: Dict[str, Module],
    producer: str,
) -> Tuple[str, ...]:
    """Modules that consume wire messages, from the call graph: walk
    forward from every function that calls ``decode_msg``; any reached
    function taking a ``msg`` parameter marks its module. The producer
    module itself is excluded (``encode_msg(msg)`` is reached too), and
    only modules where the wire analyzer can actually extract consumer
    accesses survive the filter."""
    rel_of = {id(mod): rel for rel, mod in by_rel_pkg.items()}
    roots = [
        key
        for key, node in graph.nodes.items()
        if "decode_msg" in node.call_names
    ]
    marked: set = set()
    for key in graph.reachable(roots):
        node = graph.nodes[key]
        rel = rel_of.get(id(node.mod))
        if rel is None or rel == producer:
            continue
        if "msg" in node.params():
            marked.add(rel)
    return tuple(
        rel
        for rel in sorted(marked)
        if wire_schema.extract_consumers(by_rel_pkg[rel])
    )


def run_analysis(config: Optional[Config] = None) -> AnalysisResult:
    """Parse once, build the call graph once, run the configured
    analyzers. Findings are RAW (baseline not applied — callers use
    ``load_baseline``/``apply_baseline``, or the CLI which does it for
    them)."""
    cfg = config or default_config()
    t0 = time.perf_counter()
    findings: List[Finding] = []
    matrix: Dict = {}
    consumers_used: Tuple[str, ...] = ()

    modules = list(iter_modules(cfg.package, cfg.root))
    by_rel_pkg = {
        m.path.relative_to(cfg.package).as_posix(): m for m in modules
    }
    need_graph = bool(
        {"locks", "seams", "thread"} & set(cfg.analyzers)
    ) or ("wire" in cfg.analyzers and cfg.wire_consumers is None)
    graph = callgraph.build_graph(modules) if need_graph else None

    if "locks" in cfg.analyzers:
        for mod in modules:
            findings.extend(locks.analyze_module(mod))
        findings.extend(locks.analyze_cross(modules, graph))

    if "jax" in cfg.analyzers:
        for rel, mod in by_rel_pkg.items():
            if _is_serving(rel, cfg.serving):
                findings.extend(jax_hygiene.analyze_module(mod))

    if "seams" in cfg.analyzers:
        seam_findings, matrix = seams.evaluate(graph, cfg.shapes)
        findings.extend(seam_findings)

    if "thread" in cfg.analyzers:
        findings.extend(threadctx.analyze(graph))

    if "wire" in cfg.analyzers:
        producer = by_rel_pkg.get(cfg.wire_producer)
        if producer is None:
            producer_path = cfg.package / cfg.wire_producer
            if producer_path.exists():
                producer = Module(
                    producer_path,
                    producer_path.relative_to(cfg.root).as_posix(),
                )
        if cfg.wire_consumers is not None:
            consumers_used = tuple(
                c for c in cfg.wire_consumers if c in by_rel_pkg
            )
        elif graph is not None:
            consumers_used = discover_wire_consumers(
                graph, by_rel_pkg, cfg.wire_producer
            )
        consumers = [by_rel_pkg[c] for c in consumers_used]
        if producer is not None and consumers:
            findings.extend(wire_schema.analyze(producer, consumers))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return AnalysisResult(
        findings=findings,
        contract_matrix=matrix,
        wire_consumers=consumers_used,
        wall_s=time.perf_counter() - t0,
    )


def run_analyzers(config: Optional[Config] = None) -> List[Finding]:
    """Findings-only wrapper kept for existing callers/tests."""
    return run_analysis(config).findings
