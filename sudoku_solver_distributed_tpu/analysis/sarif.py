"""SARIF 2.1.0 emitter for graftcheck findings.

SARIF is the interchange format GitHub code scanning ingests: the CI
workflow runs ``python -m …analysis --strict --sarif graftcheck.sarif``
and uploads the file, so findings annotate the PR diff inline instead
of living only in a job log. The emitter is deliberately minimal — one
run, one driver, one result per finding — and uses only stdlib types
so it stays importable everywhere the analyzers are.

Baselined findings are still emitted, carrying a ``suppressions``
entry, so the debt stays visible in the scanning UI without failing
the gate — the same philosophy as the CLI's "visible debt" output.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from .findings import Finding

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
SARIF_VERSION = "2.1.0"

# one-line rule descriptions, surfaced in the scanning UI
_RULE_FAMILIES = {
    "LOCK": "lock discipline (ordering, blocking under locks, guarded state)",
    "JAX": "JAX hygiene on the serving path (tracing, dtypes, donation)",
    "WIRE": "wire-schema drift between producer and consumers",
    "SEAM": "five-part dispatch contract coverage per dispatch shape",
    "THREAD": "blocking/expensive work reachable on singleton loop threads",
}


def _rule_description(rule: str) -> str:
    for prefix, desc in _RULE_FAMILIES.items():
        if rule.startswith(prefix):
            return desc
    return "graftcheck finding"


def _level(severity: str) -> str:
    return "error" if severity == "error" else "warning"


def _result(finding: Finding, suppressed: bool) -> Dict:
    result: Dict = {
        "ruleId": finding.rule,
        "level": _level(finding.severity),
        "message": {"text": f"{finding.symbol}: {finding.message}"},
        "locations": [
            {
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(1, finding.line)},
                },
                "logicalLocations": [
                    {"fullyQualifiedName": finding.symbol}
                ],
            }
        ],
        # stable identity across line churn: rule + file + symbol is
        # how the baseline keys findings too
        "partialFingerprints": {
            "graftcheckFindingKey/v1": (
                f"{finding.rule}:{finding.path}:{finding.symbol}"
            )
        },
    }
    if suppressed:
        result["suppressions"] = [
            {
                "kind": "external",
                "justification": "baselined in analysis/baseline.toml",
            }
        ]
    return result


def to_sarif(
    findings: Sequence[Finding],
    suppressed: Sequence[Finding] = (),
) -> Dict:
    """One SARIF log for an analysis run: ``findings`` are live,
    ``suppressed`` are baselined (emitted with a suppression record)."""
    rules_seen: List[str] = []
    for f in list(findings) + list(suppressed):
        if f.rule not in rules_seen:
            rules_seen.append(f.rule)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftcheck",
                        "informationUri": (
                            "docs/OPERATIONS.md#static-analysis"
                        ),
                        "rules": [
                            {
                                "id": rule,
                                "shortDescription": {
                                    "text": _rule_description(rule)
                                },
                            }
                            for rule in sorted(rules_seen)
                        ],
                    }
                },
                "originalUriBaseIds": {
                    "SRCROOT": {"description": {"text": "repo root"}}
                },
                "results": [
                    _result(f, suppressed=False) for f in findings
                ]
                + [_result(f, suppressed=True) for f in suppressed],
            }
        ],
    }
