"""Dispatch-contract seam analyzer (SEAM1xx) + the five-shape contract
matrix.

Every device dispatch in this repo is supposed to run under one
five-part contract, threaded by hand through five dispatch shapes
(single, batch, frontier, farm, continuous segments):

  1. **supervision** — a watchdog token opens before the device call and
     closes after it (``call_started``/``call_finished``/
     ``call_abandoned``, serving/health.py), so a hung program is
     declared, its bucket quarantined, and the breaker fed.
  2. **trace** — per-request stage stamps (``tr.mark("queue"/"coalesce"/
     "device"/"verify"/"cache")``, obs/trace.py) so a slow answer can be
     attributed to a stage.
  3. **cost** — a cost-plane record (``record_call``/``note_formation``/
     ``note_segment``/``note_farm``/``note_frontier``, obs/cost.py) so
     device spend reconciles with admission.
  4. **deadline** — the admission deadline is checked before (and,
     where the shape allows, during) dispatch, shedding expired work
     with ``DeadlineExceeded`` instead of burning device time on it.
  5. **fallback** — a reachable degraded path (``fallback_solve`` and
     friends) so a broken device demotes service instead of erroring.

This analyzer enumerates, over the shared call graph
(analysis/callgraph.py), every path from a shape's route-core entry to
its jit'd-callable invocation (the declared sink function), bridging
thread handoffs (coalescer submit → driver loop) with a declared,
validated handoff table. A leg is covered when any function on any
enumerated path — or a declared completion-side function
(``extras``, e.g. ``_finalize_padded``, which runs on the completer
thread) — directly contains that leg's marker. Coverage is the UNION
over a shape's paths: the contract is per-shape, and markers commonly
sit on exactly one spine function.

Rules (all error severity):

  SEAM101  dispatch shape has no supervision open/close on any path.
  SEAM102  dispatch shape has no trace-stage stamp on any path.
  SEAM103  dispatch shape has no cost-plane record on any path.
  SEAM104  dispatch shape has no deadline check on any path.
  SEAM105  dispatch shape has no reachable degraded fallback.
  SEAM106  shape registry rot: a declared entry/sink/handoff/extra
           symbol no longer exists, or no path connects entry to sink —
           the registry must be corrected, never left silently dead.

The ``contract_matrix`` output (``--json``) is the machine-readable
five-shape × five-leg inventory the planned ExecutionPlane refactor
consumes: for each shape it lists the witness path, per-leg coverage,
and WHICH functions currently provide each leg — i.e. exactly the code
the refactor must absorb or re-home.

Repo registry vs fixtures: with ``shapes=None`` the analyzer uses the
repo's declared shapes and silently no-ops when NONE of their entries
resolve (fixture trees); tests pass explicit :class:`ShapeSpec`\\ s.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, FuncNode
from .findings import Finding

LEGS = ("supervision", "trace", "cost", "deadline", "fallback")

_LEG_RULE = {
    "supervision": "SEAM101",
    "trace": "SEAM102",
    "cost": "SEAM103",
    "deadline": "SEAM104",
    "fallback": "SEAM105",
}

_SUPERVISION_CALLS = {"call_started", "call_finished", "call_abandoned"}
_TRACE_CALLS = {"mark", "start_trace"}
_COST_CALLS = {
    "record_call",
    "_record_call_cost",
    "note_formation",
    "note_segment",
    "note_farm",
    "note_frontier",
}

MATRIX_SCHEMA_VERSION = 1


@dataclasses.dataclass(frozen=True)
class ShapeSpec:
    """One declared dispatch shape: route-core entry, jit-invocation
    sink(s), thread handoffs bridged by queues/conditions, and
    completion-side functions whose markers count as on-path."""

    shape: str
    entry: Tuple[str, str]                    # (path suffix, symbol)
    sinks: Tuple[Tuple[str, str], ...]
    handoffs: Tuple[
        Tuple[Tuple[str, str], Tuple[str, str]], ...
    ] = ()
    extras: Tuple[Tuple[str, str], ...] = ()


# The five dispatch shapes of THIS repo. Entries are the HTTP route
# cores (net/http_api.py); the segments/single shapes share the /solve
# entry and fork at the coalescer handoff (which driver loop picks the
# request up). SEAM106 validates every symbol here against the call
# graph, so registry rot fails the gate instead of going silently dead.
REPO_SHAPES: Tuple[ShapeSpec, ...] = (
    ShapeSpec(
        shape="single",
        entry=("net/http_api.py", "solve_route"),
        sinks=(("engine.py", "SolverEngine._dispatch_padded_inner"),),
        handoffs=(
            (
                ("parallel/coalescer.py", "BatchCoalescer.submit"),
                ("parallel/coalescer.py", "BatchCoalescer._dispatcher_loop"),
            ),
        ),
        extras=(
            ("parallel/coalescer.py", "BatchCoalescer._completer_loop"),
            ("engine.py", "SolverEngine._finalize_padded"),
        ),
    ),
    ShapeSpec(
        shape="batch",
        entry=("net/http_api.py", "solve_batch_route"),
        # same jit seam as the single shape: each chunk runs through the
        # synchronous _solve_padded composition of _dispatch_padded +
        # _finalize_padded, so the supervision token and cost record ride
        # the shared seam; the finalize pair is the off-spine
        # continuation, exactly like single's completer extras
        sinks=(("engine.py", "SolverEngine._dispatch_padded_inner"),),
        extras=(
            ("engine.py", "SolverEngine._finalize_padded"),
            ("engine.py", "SolverEngine._finalize_padded_inner"),
        ),
    ),
    ShapeSpec(
        shape="frontier",
        entry=("net/http_api.py", "solve_route"),
        sinks=(("parallel/frontier.py", "frontier_solve"),),
    ),
    ShapeSpec(
        shape="farm",
        entry=("net/http_api.py", "solve_route"),
        sinks=(("net/node.py", "P2PNode._farm_solve"),),
    ),
    ShapeSpec(
        shape="segments",
        entry=("net/http_api.py", "solve_route"),
        sinks=(("engine.py", "SolverEngine.dispatch_segment"),),
        handoffs=(
            (
                ("parallel/coalescer.py", "BatchCoalescer.submit"),
                ("parallel/coalescer.py", "BatchCoalescer._segment_loop"),
            ),
            (
                ("parallel/coalescer.py", "BatchCoalescer.submit"),
                (
                    "parallel/coalescer.py",
                    "BatchCoalescer._segment_loop_pipelined",
                ),
            ),
        ),
        extras=(("engine.py", "SolverEngine.finalize_segment"),),
    ),
)


def _compares_deadline(node: FuncNode) -> bool:
    for sub in ast.walk(node.fn):
        if not isinstance(sub, ast.Compare):
            continue
        for name_node in ast.walk(sub):
            ident = None
            if isinstance(name_node, ast.Name):
                ident = name_node.id
            elif isinstance(name_node, ast.Attribute):
                ident = name_node.attr
            if ident is not None and "deadline" in ident.lower():
                return True
    return False


def leg_markers(node: FuncNode) -> Dict[str, bool]:
    """Which of the five contract legs this one function directly
    carries a marker for."""
    names = node.call_names
    idents = node.identifiers
    return {
        "supervision": bool(names & _SUPERVISION_CALLS),
        "trace": bool(names & _TRACE_CALLS),
        "cost": bool(names & _COST_CALLS),
        "deadline": (
            "DeadlineExceeded" in idents or _compares_deadline(node)
        ),
        "fallback": any("fallback" in i.lower() for i in idents),
    }


def evaluate(
    graph: CallGraph,
    shapes: Optional[Sequence[ShapeSpec]] = None,
) -> Tuple[List[Finding], Dict]:
    """(findings, contract matrix) for the given shapes.

    ``shapes=None`` uses :data:`REPO_SHAPES`; if none of their entries
    resolve (a fixture tree), the result is empty rather than a wall of
    SEAM106 noise about a registry that was never meant to describe the
    analyzed tree.
    """
    registry_mode = shapes is None
    specs = REPO_SHAPES if shapes is None else tuple(shapes)
    findings: List[Finding] = []
    matrix: Dict = {
        "schema_version": MATRIX_SCHEMA_VERSION,
        "legs": list(LEGS),
        "shapes": [],
    }
    if registry_mode and not any(
        graph.find(*spec.entry) for spec in specs
    ):
        return findings, matrix

    for spec in specs:
        entry_key = graph.find(*spec.entry)
        missing: List[str] = []
        if entry_key is None:
            missing.append(f"entry {spec.entry[0]}::{spec.entry[1]}")
        sink_keys: Set[str] = set()
        for ref in spec.sinks:
            key = graph.find(*ref)
            if key is None:
                missing.append(f"sink {ref[0]}::{ref[1]}")
            else:
                sink_keys.add(key)
        extra_edges: Dict[str, List[str]] = {}
        for src_ref, dst_ref in spec.handoffs:
            src = graph.find(*src_ref)
            dst = graph.find(*dst_ref)
            if src is None:
                missing.append(f"handoff {src_ref[0]}::{src_ref[1]}")
            if dst is None:
                missing.append(f"handoff {dst_ref[0]}::{dst_ref[1]}")
            if src is not None and dst is not None:
                extra_edges.setdefault(src, []).append(dst)
        extra_keys: Set[str] = set()
        for ref in spec.extras:
            key = graph.find(*ref)
            if key is None:
                missing.append(f"extra {ref[0]}::{ref[1]}")
            else:
                extra_keys.add(key)
        if missing:
            findings.append(
                _shape_finding(
                    graph,
                    spec,
                    entry_key,
                    "SEAM106",
                    "shape registry rot: "
                    + "; ".join(missing)
                    + " not found in the call graph — fix the "
                    "registry in analysis/seams.py",
                )
            )
            continue

        paths = graph.paths(entry_key, sink_keys, extra_edges)
        if not paths:
            findings.append(
                _shape_finding(
                    graph,
                    spec,
                    entry_key,
                    "SEAM106",
                    f"no dispatch path from "
                    f"{spec.entry[1]} to any declared sink — the "
                    f"shape registry no longer matches the code",
                )
            )
            continue

        on_path: Set[str] = set()
        for trail in paths:
            on_path.update(trail)
        on_path |= extra_keys

        coverage: Dict[str, List[str]] = {leg: [] for leg in LEGS}
        for key in sorted(on_path):
            marks = leg_markers(graph.nodes[key])
            for leg in LEGS:
                if marks[leg]:
                    coverage[leg].append(key)

        witness = min(paths, key=len)
        matrix["shapes"].append(
            {
                "shape": spec.shape,
                "entry": entry_key,
                "sinks": sorted(sink_keys),
                "paths": len(paths),
                "witness": witness,
                "covered": {
                    leg: bool(coverage[leg]) for leg in LEGS
                },
                "provided_by": {
                    leg: coverage[leg] for leg in LEGS
                },
            }
        )
        for leg in LEGS:
            if not coverage[leg]:
                findings.append(
                    _shape_finding(
                        graph,
                        spec,
                        entry_key,
                        _LEG_RULE[leg],
                        f"dispatch shape {spec.shape!r} "
                        f"({spec.entry[1]} → "
                        f"{spec.sinks[0][1]}) has no {leg} leg on any "
                        f"of its {len(paths)} path(s) — the five-part "
                        f"dispatch contract requires one on the spine",
                    )
                )
    return findings, matrix


def analyze(
    graph: CallGraph,
    shapes: Optional[Sequence[ShapeSpec]] = None,
) -> List[Finding]:
    return evaluate(graph, shapes)[0]


def contract_matrix(
    graph: CallGraph,
    shapes: Optional[Sequence[ShapeSpec]] = None,
) -> Dict:
    return evaluate(graph, shapes)[1]


def _shape_finding(
    graph: CallGraph,
    spec: ShapeSpec,
    entry_key: Optional[str],
    rule: str,
    message: str,
) -> Finding:
    if entry_key is not None:
        node = graph.nodes[entry_key]
        path, line = node.mod.rel_path, node.fn.lineno
    else:
        path, line = spec.entry[0], 1
    return Finding(
        rule, "error", path, line, f"dispatch:{spec.shape}", message
    )
