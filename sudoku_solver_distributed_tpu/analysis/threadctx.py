"""Thread-context analyzer (THREAD1xx): what runs on the singleton loop
threads.

This process interleaves an HTTP surface, a UDP gossip loop, and device
dispatch in one interpreter — so a handful of SINGLETON LOOP THREADS are
latency-critical shared infrastructure: the UDP receive loop
(``P2PNode.run``), the coalescer's dispatcher/completer/segment drivers,
and the engine watchdog. Anything expensive or indefinitely blocking
that becomes reachable on one of them stalls every request behind it.
Both recorded incidents of this class were found at runtime, late:
PR 13 (``canonicalize`` on the UDP thread, ~0.5 ms per datagram) and
PR 15 (full-queue sorts on the segment driver). These rules make the
class mechanical.

Loop-thread discovery is structural, from the shared call graph's
``threading.Thread(...)`` index: a spawn is a singleton loop when its
handle or name marks it a singleton (constant ``name=`` string, or a
``self.X = Thread(...)`` assignment), it is NOT constructed inside a
loop statement (pool idiom, e.g. ``fastserve-worker-{n}``), and its
target function contains a ``while`` loop. The UDP loop is added by
registry (it runs on the MAIN thread by construction — ``run()`` is
called, not spawned). Deliberate offload threads — whose entire purpose
is to absorb blocking/expensive work — are exempted by the registry
below, each with its reason; the exemption list is validated against
the graph (THREAD105) so it can never rot into silently exempting
nothing.

Rules (all error severity):

  THREAD101  expensive CPU call (``oracle_solve``, ``canonicalize``)
             reachable on a singleton loop thread via the call graph.
  THREAD102  indefinite blocking wait reachable on a loop thread
             through a CALLEE: zero-argument ``.get()``/``.wait()``/
             ``.join()``, any ``.result()`` without a timeout, or
             ``.accept()``. The loop function's OWN top-level wait is
             exempt — that wait IS the loop's scheduler (e.g. the
             completer's ``_inflight.get()``); buried in a callee it is
             an unbounded stall nobody scheduled.
  THREAD103  ``time.sleep`` with a constant budget > 1 s reachable on a
             loop thread — a loop parked that long misses deadlines;
             long sleeps belong on offload threads or interval waits.
  THREAD104  full-collection sort (``sorted(self.X…)``/``self.X.sort()``)
             of a GROWABLE shared attribute (one the class appends to)
             reachable on a loop thread — the PR 15 bug class; use a
             bounded selection (``heapq.nsmallest``) instead.
  THREAD105  registry rot: an exemption or extra-root entry below
             matches nothing in the analyzed tree.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ._astutil import self_attr
from .callgraph import CallGraph, FuncNode, ThreadSpawn
from .findings import Finding

_EXPENSIVE = {"oracle_solve", "canonicalize"}

# loop roots that are not Thread spawns: the UDP receive loop runs on
# the process main thread by construction (cli calls node.run())
REPO_EXTRA_ROOTS: Tuple[Tuple[str, str, str], ...] = (
    ("net/node.py", "P2PNode.run", "udp-loop"),
)

# deliberate offload/management threads: blocking or expensive work on
# them is their PURPOSE, not a hazard. Entries match a spawn's constant
# name= string or the resolved target symbol.
REPO_EXEMPT: Tuple[Tuple[str, str], ...] = (
    ("name", "coalescer-prestage"),     # host-staging offload (PR 15)
    ("name", "coalescer-deep-retry"),   # one-shot deep-budget retry
    ("name", "autopilot"),              # management loop; prewarm work
                                        # (canonicalize) is deliberate
    ("name", "cache-prewarm"),          # bulk verify/store offload
    ("name", "engine-warmup"),          # compile thread
    ("name", "fanout-warm"),            # compile thread
    ("target", "P2PNode._worker_loop"),  # the PR 13 offload worker:
                                         # absorbs solve tasks so the
                                         # UDP loop never does
    ("target", "FrontierServingLoop._run"),  # mesh collective loop:
                                             # device roundtrips are
                                             # its entire job
)


@dataclasses.dataclass(frozen=True)
class LoopRoot:
    key: str        # call-graph node key of the loop function
    label: str      # human name ("coalescer-dispatch", "udp-loop", …)


def _resolve_target(
    graph: CallGraph, spawn: ThreadSpawn
) -> Optional[str]:
    if spawn.target is None:
        return None
    owner = graph.nodes.get(spawn.owner)
    if owner is not None and owner.cls_name is not None:
        methods = graph.methods.get(
            (owner.mod.rel_path, owner.cls_name), {}
        )
        if spawn.target in methods:
            return methods[spawn.target]
    if owner is not None:
        # nested defs / module functions of the spawning module — prefer
        # a def nested in the OWNER (deep-retry's `run`) over the
        # module-level index entry
        nested = f"{owner.key}.{spawn.target}"
        if nested in graph.nodes:
            return nested
        local = graph.module_funcs.get(owner.mod.rel_path, {})
        if spawn.target in local:
            return local[spawn.target]
    keys = graph.by_name.get(spawn.target, [])
    if len(keys) == 1:
        return keys[0]
    return None


def discover_roots(
    graph: CallGraph,
    extra_roots: Sequence[Tuple[str, str, str]],
    exempt: Sequence[Tuple[str, str]],
) -> Tuple[List[LoopRoot], Set[Tuple[str, str]]]:
    """(singleton loop roots, registry entries that matched something)."""
    roots: List[LoopRoot] = []
    matched: Set[Tuple[str, str]] = set()
    exempt_names = {v for k, v in exempt if k == "name"}
    exempt_targets = {v for k, v in exempt if k == "target"}
    seen_keys: Set[str] = set()
    for spawn in graph.spawns:
        if spawn.in_loop or spawn.dynamic_name:
            continue  # pool idiom
        if spawn.thread_name is None and not spawn.on_self:
            continue  # fire-and-forget helper thread
        target_key = _resolve_target(graph, spawn)
        if target_key is None:
            continue
        node = graph.nodes[target_key]
        if spawn.thread_name in exempt_names:
            matched.add(("name", spawn.thread_name))
            continue
        if node.symbol in exempt_targets:
            matched.add(("target", node.symbol))
            continue
        if not node.has_while:
            continue  # one-shot worker (probe, freeze hook, …)
        if target_key in seen_keys:
            continue
        seen_keys.add(target_key)
        roots.append(
            LoopRoot(target_key, spawn.thread_name or node.symbol)
        )
    for path_suffix, symbol, label in extra_roots:
        key = graph.find(path_suffix, symbol)
        if key is None:
            continue
        matched.add(("root", f"{path_suffix}::{symbol}"))
        if key not in seen_keys:
            seen_keys.add(key)
            roots.append(LoopRoot(key, label))
    return roots, matched


def _chain(
    graph: CallGraph, root: str, target: str
) -> List[str]:
    """Shortest call chain root→target, as symbols, for messages."""
    parents: Dict[str, str] = {root: root}
    frontier = [root]
    while frontier and target not in parents:
        nxt: List[str] = []
        for key in frontier:
            for callee, _site in graph.edges.get(key, ()):
                if callee not in parents:
                    parents[callee] = key
                    nxt.append(callee)
        frontier = nxt
    if target not in parents:
        return []
    chain = [target]
    while chain[-1] != root:
        chain.append(parents[chain[-1]])
    return [graph.nodes[k].symbol for k in reversed(chain)]


def _fmt_chain(symbols: List[str]) -> str:
    if len(symbols) > 6:
        symbols = symbols[:3] + ["…"] + symbols[-2:]
    return " → ".join(symbols)


def _grows(node: FuncNode, attr: str) -> bool:
    """Does the node's class append/extend ``self.<attr>`` anywhere —
    i.e. is the attribute a growable queue rather than a small fixed
    tuple/list?"""
    if node.cls_name is None:
        return False
    for stmt in node.mod.tree.body:
        if (
            isinstance(stmt, ast.ClassDef)
            and stmt.name == node.cls_name
        ):
            for sub in ast.walk(stmt):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("append", "extend", "insert")
                    and self_attr(sub.func.value) == attr
                ):
                    return True
    return False


def _mentions_self_attr(expr: ast.AST) -> Optional[str]:
    for sub in ast.walk(expr):
        name = self_attr(sub)
        if name is not None:
            return name
    return None


def _scan_node(
    graph: CallGraph,
    node: FuncNode,
    root: LoopRoot,
    is_root_fn: bool,
    findings: List[Finding],
    seen: Set[Tuple[str, str, int]],
) -> None:
    chain_cache: Optional[str] = None

    def chain() -> str:
        nonlocal chain_cache
        if chain_cache is None:
            chain_cache = _fmt_chain(
                _chain(graph, root.key, node.key) or [node.symbol]
            )
        return chain_cache

    def add(rule: str, line: int, msg: str):
        dedup = (rule, node.key, line)
        if dedup in seen:
            return
        seen.add(dedup)
        findings.append(
            Finding(
                rule, "error", node.mod.rel_path, line, node.symbol, msg
            )
        )

    for site in node.calls:
        # THREAD101: expensive CPU work
        if site.name in _EXPENSIVE:
            add(
                "THREAD101",
                site.line,
                f"{site.name}() runs on singleton loop thread "
                f"{root.label!r} ({chain()}) — move it to the waiting/"
                f"offload thread; the loop must stay cheap",
            )
        # THREAD103: long parked sleep
        if site.dotted == "time.sleep" and site.call.args:
            arg = site.call.args[0]
            if (
                isinstance(arg, ast.Constant)
                and isinstance(arg.value, (int, float))
                and arg.value > 1.0
            ):
                add(
                    "THREAD103",
                    site.line,
                    f"time.sleep({arg.value}) parks loop thread "
                    f"{root.label!r} ({chain()}) — a loop stalled "
                    f"past 1 s misses deadlines; use an interval "
                    f"wait or an offload thread",
                )
        # THREAD104: full sort of growable shared state
        if site.name == "sorted" and site.kind == "name" and site.call.args:
            attr = _mentions_self_attr(site.call.args[0])
            if attr is not None and _grows(node, attr):
                add(
                    "THREAD104",
                    site.line,
                    f"sorted(self.{attr}) on loop thread "
                    f"{root.label!r} ({chain()}) — full sort of a "
                    f"growable queue is O(n log n) per wakeup (the "
                    f"PR 15 driver-stall class); take a bounded "
                    f"selection (heapq.nsmallest) instead",
                )
        if site.name == "sort" and site.kind != "name":
            func = site.call.func
            attr = (
                self_attr(func.value)
                if isinstance(func, ast.Attribute)
                else None
            )
            if attr is not None and _grows(node, attr):
                add(
                    "THREAD104",
                    site.line,
                    f"self.{attr}.sort() on loop thread "
                    f"{root.label!r} ({chain()}) — full sort of a "
                    f"growable queue on the loop; use a bounded "
                    f"selection (heapq.nsmallest)",
                )
        # THREAD102: indefinite blocking waits, callees only (the
        # root's own top-level wait is its scheduler)
        if is_root_fn or site.deferred:
            continue
        blocked: Optional[str] = None
        has_timeout = any(
            kw.arg == "timeout" for kw in site.call.keywords
        )
        argless = not site.call.args and not site.call.keywords
        if site.name in ("get", "wait", "join") and argless:
            blocked = f".{site.name}() with no timeout"
        elif site.name == "result" and not has_timeout and not (
            site.call.args
        ):
            blocked = ".result() with no timeout"
        elif site.name == "accept" and site.kind != "name":
            blocked = ".accept()"
        if blocked is not None:
            add(
                "THREAD102",
                site.line,
                f"indefinite {blocked} reachable on loop thread "
                f"{root.label!r} ({chain()}) — a wait the loop "
                f"didn't schedule can stall it forever; bound it "
                f"with a timeout or move it off the loop",
            )


def analyze(
    graph: CallGraph,
    extra_roots: Optional[Sequence[Tuple[str, str, str]]] = None,
    exempt: Optional[Sequence[Tuple[str, str]]] = None,
) -> List[Finding]:
    registry_mode = extra_roots is None and exempt is None
    extra = REPO_EXTRA_ROOTS if extra_roots is None else tuple(extra_roots)
    exem = REPO_EXEMPT if exempt is None else tuple(exempt)
    findings: List[Finding] = []
    roots, matched = discover_roots(graph, extra, exem)

    # THREAD105 registry-rot: only when the registry plausibly describes
    # this tree (at least one entry matched) — fixture trees analyzed
    # with the repo defaults must not drown in rot noise
    if matched or not registry_mode:
        spawn_names = {s.thread_name for s in graph.spawns}
        symbols = {n.symbol for n in graph.nodes.values()}
        stale: List[str] = []
        for kind, value in exem:
            if (kind, value) in matched:
                continue
            exists = (
                value in spawn_names
                if kind == "name"
                else value in symbols
            )
            if not exists:
                stale.append(f"{kind}:{value}")
        for path_suffix, symbol, _label in extra:
            if graph.find(path_suffix, symbol) is None:
                stale.append(f"root:{path_suffix}::{symbol}")
        if stale:
            findings.append(
                Finding(
                    "THREAD105",
                    "error",
                    "sudoku_solver_distributed_tpu/analysis/threadctx.py",
                    1,
                    "<registry>",
                    "thread registry rot: "
                    + ", ".join(sorted(stale))
                    + " matches nothing — fix the registry",
                )
            )

    seen: Set[Tuple[str, str, int]] = set()
    for root in roots:
        for key in sorted(graph.reachable([root.key])):
            _scan_node(
                graph,
                graph.nodes[key],
                root,
                is_root_fn=(key == root.key),
                findings=findings,
                seen=seen,
            )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
