"""Wire-schema drift analyzer (WIRE1xx).

The UDP protocol is JSON dicts built by ``net/wire.py`` constructors and
consumed by hand dispatch in ``net/node.py`` (plus the helpers it hands
messages to). Nothing but convention keeps the two sides aligned — the
goodbye-vs-rumor bug class fixed in PR 2 was this drift. This analyzer
recovers both sides from source:

  * **producers**: every ``wire.py`` function returning dict literals
    with a constant ``"type"`` key. Multiple returns give per-type
    variants: a key in every variant is *required*, a key in some is
    *optional* (``disconnect`` carries row/col only at shutdown).
  * **consumers**: every function with a ``msg`` parameter. A dispatch
    function compares ``msg["type"]``/``msg.get("type")`` against string
    constants; key accesses are attributed to the message types the
    enclosing branch's tests allow (``==``, ``in (tuple)``), hard
    subscripts ``msg["k"]`` tracked separately from tolerant
    ``msg.get("k")``/``"k" in msg``. One level of intra-class/module
    ``helper(msg)`` calls is followed (to a fixed point), so
    ``self._on_disconnect(msg)``'s accesses count for the disconnect
    branch.

Rules:

  WIRE101 (error)   a consumer branch for type T hard-subscripts a key
                    no constructor of T ever emits → KeyError on every
                    such message.
  WIRE102 (error)   hard-subscript of a key only SOME variants of T
                    emit → KeyError on the variants without it.
  WIRE103 (warning) consumed-but-never-produced / produced-but-never-
                    consumed message types (dead or phantom messages).
  WIRE104 (warning) a ``msg`` key accessed anywhere (typed or not) that
                    no constructor emits at all — drift smell even when
                    the dispatch attribution can't see the type.
  WIRE105 (warning) a dict literal with a ``"type"`` key constructed in
                    a consumer module — wire messages belong in the
                    producer module, where this analyzer (and the
                    goldens) can see their schema.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ._astutil import Module, const_str
from .findings import Finding

MSG_PARAM = "msg"


# -- producer side -----------------------------------------------------------

@dataclasses.dataclass
class ProducerSchema:
    """Per message type: key sets of each return-dict variant."""

    variants: List[Tuple[str, int, Set[str]]] = dataclasses.field(
        default_factory=list
    )  # (function, line, keys)

    @property
    def all_keys(self) -> Set[str]:
        out: Set[str] = set()
        for _f, _l, keys in self.variants:
            out |= keys
        return out

    @property
    def required_keys(self) -> Set[str]:
        out: Optional[Set[str]] = None
        for _f, _l, keys in self.variants:
            out = set(keys) if out is None else out & keys
        return out or set()


def extract_producers(mod: Module) -> Dict[str, ProducerSchema]:
    """type → schema from every function returning dict literals with a
    constant "type" entry."""
    schemas: Dict[str, ProducerSchema] = {}
    for fn in mod.functions():
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            for d in _dict_literals(node.value):
                keys = _dict_keys(d)
                if keys is None or "type" not in keys:
                    continue
                mtype = _dict_type_value(d)
                if mtype is None:
                    continue
                schema = schemas.setdefault(mtype, ProducerSchema())
                schema.variants.append((fn.name, d.lineno, keys))
    return schemas


def _dict_literals(expr: ast.expr) -> List[ast.Dict]:
    return [n for n in ast.walk(expr) if isinstance(n, ast.Dict)]


def _dict_keys(d: ast.Dict) -> Optional[Set[str]]:
    keys: Set[str] = set()
    for k in d.keys:
        s = const_str(k) if k is not None else None
        if s is None:
            return None  # computed/splatted key: schema unknowable
        keys.add(s)
    return keys


def _dict_type_value(d: ast.Dict) -> Optional[str]:
    for k, v in zip(d.keys, d.values):
        if k is not None and const_str(k) == "type":
            return const_str(v)
    return None


# -- consumer side -----------------------------------------------------------

@dataclasses.dataclass
class _Access:
    key: str
    line: int
    hard: bool                      # msg["k"] vs msg.get("k") / "k" in msg
    types: Optional[Tuple[str, ...]]  # constrained types; None = any


class _ConsumerWalker:
    """Collect key accesses on the ``msg`` param of one function,
    attributed to the message types the enclosing branches allow."""

    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.accesses: List[_Access] = []
        self.helper_calls: List[Tuple[str, Optional[Tuple[str, ...]]]] = []
        # types this function's branches dispatch on — consumption
        # evidence even when the branch body hands msg straight to a
        # cross-module helper (e.g. self.stats.merge(msg))
        self.dispatched_types: Set[str] = set()
        # names bound to msg["type"] / msg.get("type")
        self.type_aliases: Set[str] = set()
        self._prescan_aliases()
        self._walk(fn.body, None)

    def _prescan_aliases(self):
        # a name is a type alias only if EVERY assignment to it is a
        # msg["type"]/msg.get("type") read — one rebinding to anything
        # else (e.g. `t = msg.get("kind")`) and branch tests on it must
        # not be attributed to wire message types
        rebound: Set[str] = set()
        for node in ast.walk(self.fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if not isinstance(t, ast.Name):
                    continue
                if self._is_type_access(node.value):
                    self.type_aliases.add(t.id)
                else:
                    rebound.add(t.id)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(node.target, ast.Name):
                    rebound.add(node.target.id)
        self.type_aliases -= rebound

    def _is_type_access(self, expr: ast.expr) -> bool:
        if (
            isinstance(expr, ast.Subscript)
            and _is_msg(expr.value)
            and const_str(_slice(expr)) == "type"
        ):
            return True
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "get"
            and _is_msg(expr.func.value)
            and expr.args
            and const_str(expr.args[0]) == "type"
        ):
            return True
        return False

    # -- type constraints --------------------------------------------------
    def _types_from_test(
        self, test: ast.expr
    ) -> Optional[Tuple[str, ...]]:
        """The message types a branch test constrains to, or None."""
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            for v in test.values:
                got = self._types_from_test(v)
                if got is not None:
                    return got
            return None
        if not isinstance(test, ast.Compare) or len(test.ops) != 1:
            return None
        left, op, right = test.left, test.ops[0], test.comparators[0]
        if not (
            (isinstance(left, ast.Name) and left.id in self.type_aliases)
            or self._is_type_access(left)
        ):
            return None
        if isinstance(op, ast.Eq):
            s = const_str(right)
            return (s,) if s is not None else None
        if isinstance(op, ast.In) and isinstance(
            right, (ast.Tuple, ast.List, ast.Set)
        ):
            vals = [const_str(e) for e in right.elts]
            if all(v is not None for v in vals):
                return tuple(vals)  # type: ignore[arg-type]
        return None

    # -- walk --------------------------------------------------------------
    def _walk(self, body: List[ast.stmt], types: Optional[Tuple[str, ...]]):
        for stmt in body:
            if isinstance(stmt, ast.If):
                constrained = self._types_from_test(stmt.test)
                branch_types = constrained or types
                if constrained:
                    self.dispatched_types |= set(constrained)
                # short-circuit: msg accesses in an `mtype == T and ...`
                # test only evaluate once the type check passed, so they
                # belong to the branch's types
                self._scan_expr(stmt.test, branch_types)
                self._walk(stmt.body, branch_types)
                self._walk(stmt.orelse, types)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(stmt.body, types)
                continue
            for field, value in ast.iter_fields(stmt):
                if field in ("body", "orelse", "finalbody"):
                    if isinstance(value, list):
                        self._walk(
                            [s for s in value if isinstance(s, ast.stmt)],
                            types,
                        )
                    continue
                if field == "handlers":
                    for h in value or []:
                        self._walk(h.body, types)
                    continue
                if isinstance(value, ast.expr):
                    self._scan_expr(value, types)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._scan_expr(v, types)

    def _scan_expr(
        self, expr: ast.expr, types: Optional[Tuple[str, ...]]
    ):
        for node in ast.walk(expr):
            if isinstance(node, ast.Subscript) and _is_msg(node.value):
                key = const_str(_slice(node))
                if key is not None and key != "type":
                    self.accesses.append(
                        _Access(key, node.lineno, True, types)
                    )
            elif isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "get"
                    and _is_msg(func.value)
                    and node.args
                ):
                    key = const_str(node.args[0])
                    if key is not None and key != "type":
                        self.accesses.append(
                            _Access(key, node.lineno, False, types)
                        )
                elif any(
                    _is_msg(a) for a in node.args
                ):
                    callee = func.attr if isinstance(
                        func, ast.Attribute
                    ) else (func.id if isinstance(func, ast.Name) else None)
                    if callee is not None:
                        self.helper_calls.append((callee, types))
            elif isinstance(node, ast.Compare) and any(
                _is_msg(c) for c in node.comparators
            ):
                # "key" in msg
                if len(node.ops) == 1 and isinstance(
                    node.ops[0], (ast.In, ast.NotIn)
                ):
                    key = const_str(node.left)
                    if key is not None and key != "type":
                        self.accesses.append(
                            _Access(key, node.lineno, False, None)
                        )


def _is_msg(node: ast.expr) -> bool:
    return isinstance(node, ast.Name) and node.id == MSG_PARAM


def _slice(node: ast.Subscript) -> ast.expr:
    s = node.slice
    return s.value if isinstance(s, ast.Index) else s  # py<3.9 compat


def extract_consumers(
    mod: Module,
) -> Dict[str, _ConsumerWalker]:
    """function symbol → walker, for every function taking a ``msg``
    param; helper accesses folded into callers to a fixed point."""
    walkers: Dict[str, _ConsumerWalker] = {}
    by_name: Dict[str, str] = {}
    for cls in mod.classes():
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _takes_msg(node):
                    symbol = f"{cls.name}.{node.name}"
                    walkers[symbol] = _ConsumerWalker(node)
                    by_name[node.name] = symbol
    for fn in mod.functions():
        if _takes_msg(fn):
            walkers[fn.name] = _ConsumerWalker(fn)
            by_name.setdefault(fn.name, fn.name)

    # fold helper accesses into callers (fixed point; helper accesses
    # inherit the CALL SITE's type constraint when the helper itself had
    # none)
    changed = True
    guard = 0
    while changed and guard < 20:
        changed = False
        guard += 1
        for symbol, w in walkers.items():
            for callee, call_types in w.helper_calls:
                target = by_name.get(callee)
                if target is None or target == symbol:
                    continue
                for acc in walkers[target].accesses:
                    merged = _Access(
                        acc.key,
                        acc.line,
                        acc.hard,
                        acc.types if acc.types is not None else call_types,
                    )
                    if not _has_access(w.accesses, merged):
                        w.accesses.append(merged)
                        changed = True
    return walkers


def _has_access(accesses: List[_Access], a: _Access) -> bool:
    return any(
        x.key == a.key
        and x.line == a.line
        and x.hard == a.hard
        and x.types == a.types
        for x in accesses
    )


def _takes_msg(fn: ast.FunctionDef) -> bool:
    return any(
        a.arg == MSG_PARAM
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
    )


# -- the drift check ---------------------------------------------------------

def analyze(
    producer_mod: Module, consumer_mods: List[Module]
) -> List[Finding]:
    findings: List[Finding] = []
    schemas = extract_producers(producer_mod)
    produced_types = set(schemas)
    consumed_types: Set[str] = set()
    all_produced_keys: Set[str] = set()
    for s in schemas.values():
        all_produced_keys |= s.all_keys

    for mod in consumer_mods:
        walkers = extract_consumers(mod)
        for symbol, w in walkers.items():
            consumed_types |= w.dispatched_types
            for acc in w.accesses:
                if acc.types is not None:
                    consumed_types |= set(acc.types)
                types = acc.types
                if types is None:
                    if acc.key not in all_produced_keys and acc.hard:
                        findings.append(
                            Finding(
                                "WIRE104",
                                "warning",
                                mod.rel_path,
                                acc.line,
                                symbol,
                                f"msg[{acc.key!r}] accessed but no "
                                f"wire constructor emits a "
                                f"{acc.key!r} key at all",
                            )
                        )
                    continue
                for t in types:
                    if t not in schemas:
                        continue  # WIRE103 covers unknown types
                    schema = schemas[t]
                    if acc.hard and acc.key not in schema.all_keys:
                        findings.append(
                            Finding(
                                "WIRE101",
                                "error",
                                mod.rel_path,
                                acc.line,
                                symbol,
                                f"handler for type {t!r} subscripts "
                                f"msg[{acc.key!r}] but no "
                                f"constructor of {t!r} emits that key "
                                f"(produced: "
                                f"{sorted(schema.all_keys)})",
                            )
                        )
                    elif (
                        acc.hard
                        and acc.key not in schema.required_keys
                    ):
                        variants = [
                            f
                            for f, _l, keys in schema.variants
                            if acc.key not in keys
                        ]
                        findings.append(
                            Finding(
                                "WIRE102",
                                "error",
                                mod.rel_path,
                                acc.line,
                                symbol,
                                f"handler for type {t!r} subscripts "
                                f"msg[{acc.key!r}], which only some "
                                f"variants emit (missing from "
                                f"{sorted(set(variants))}) — use "
                                f".get() or handle KeyError",
                            )
                        )
        # WIRE105: inline wire-message construction in consumer modules
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Dict):
                keys = _dict_keys(node)
                if keys and "type" in keys and _dict_type_value(node):
                    findings.append(
                        Finding(
                            "WIRE105",
                            "warning",
                            mod.rel_path,
                            node.lineno,
                            "<module>",
                            f"inline wire message "
                            f"{{'type': "
                            f"{_dict_type_value(node)!r}, ...}} "
                            f"constructed outside the producer module "
                            f"— add/use a constructor in wire.py",
                        )
                    )

    # WIRE103: types produced but never consumed / consumed but never
    # produced
    for t in sorted(produced_types - consumed_types):
        f, line, _keys = schemas[t].variants[0]
        findings.append(
            Finding(
                "WIRE103",
                "warning",
                producer_mod.rel_path,
                line,
                f,
                f"message type {t!r} is produced but no handler "
                f"dispatches on it",
            )
        )
    for t in sorted(consumed_types - produced_types):
        findings.append(
            Finding(
                "WIRE103",
                "warning",
                producer_mod.rel_path,
                1,
                "<module>",
                f"message type {t!r} is consumed but no constructor "
                f"produces it",
            )
        )
    return findings
