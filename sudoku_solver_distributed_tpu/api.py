"""Host-facing ``Sudoku`` class — the reference's public board API.

Surface-compatible with reference sudoku.py:5-140: same constructor signature,
``grid`` attribute, ANSI ``__str__``, ``update_row`` / ``update_column``
helpers, and the rate-limited ``check_is_valid`` / ``check_row`` /
``check_column`` / ``check_square`` / ``check`` validation methods (including
the per-call ``base_delay`` / ``interval`` / ``threshold`` overrides).

The implementation is TPU-native: every check dispatches to the batched
bitmask kernels (ops/validate.py) through cached jitted entry points, so the
same code path validates one hosted board here and a million-board batch in
the engine. The handicap rate limiter (reference sudoku.py:13-30) gates these
host-facing calls only — it is the course's simulated compute cost, not a
property of the device kernels.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .ops import spec_for_size
from .ops.validate import (
    check_boards,
    check_boxes,
    check_cols,
    check_rows,
    is_valid_move,
)
from .utils import HandicapLimiter, render_board_highlight_zeros


@functools.lru_cache(maxsize=None)
def _kernels(size: int):
    """Jitted single-board validation kernels for a given board size."""
    spec = spec_for_size(size)
    return {
        "board": jax.jit(lambda g: check_boards(g, spec)),
        "rows": jax.jit(lambda g: check_rows(g, spec)),
        "cols": jax.jit(lambda g: check_cols(g, spec)),
        "boxes": jax.jit(lambda g: check_boxes(g, spec)),
        "move": jax.jit(
            lambda g, r, c, v: is_valid_move(g, r, c, v, spec)
        ),
    }


class Sudoku:
    """A hosted board with rate-limited validation (reference sudoku.py:5-140)."""

    def __init__(
        self,
        sudoku: Sequence[Sequence[int]],
        base_delay: float = 0.01,
        interval: float = 10,
        threshold: int = 5,
    ):
        self.grid: List[List[int]] = [list(r) for r in sudoku]
        self.base_delay = base_delay
        self.interval = interval
        self.threshold = threshold
        self._limiter = HandicapLimiter(base_delay, interval, threshold)
        self._size = len(self.grid)
        self._spec = spec_for_size(self._size)
        # number of rate-limited validation calls made through this object —
        # the accounting unit of reference node.py:87
        self.validations = 0

    # -- rendering ---------------------------------------------------------
    def __str__(self) -> str:
        return render_board_highlight_zeros(self.grid)

    # -- mutation helpers (reference sudoku.py:51-58) ----------------------
    def update_row(self, row: int, values: Sequence[int]) -> None:
        self.grid[row] = list(values)

    def update_column(self, col: int, values: Sequence[int]) -> None:
        for row in range(self._size):
            self.grid[row][col] = values[row]

    # -- validation surface ------------------------------------------------
    def _tick(self, base_delay, interval, threshold) -> None:
        self.validations += 1
        self._limiter.tick(base_delay, interval, threshold)

    def _device_grid(self) -> jnp.ndarray:
        return jnp.asarray(np.asarray(self.grid, np.int32)[None])

    def check_is_valid(
        self, row: int, col: int, num: int,
        base_delay=None, interval=None, threshold=None,
    ) -> bool:
        """True iff ``num`` appears nowhere in the row/col/box of (row, col)
        (the queried cell included — reference sudoku.py:60-78 semantics)."""
        self._tick(base_delay, interval, threshold)
        out = _kernels(self._size)["move"](
            self._device_grid(),
            jnp.int32(row), jnp.int32(col), jnp.int32(num),
        )
        return bool(out[0])

    def check_row(self, row: int, base_delay=None, interval=None, threshold=None) -> bool:
        self._tick(base_delay, interval, threshold)
        return bool(_kernels(self._size)["rows"](self._device_grid())[0, row])

    def check_column(self, col: int, base_delay=None, interval=None, threshold=None) -> bool:
        self._tick(base_delay, interval, threshold)
        return bool(_kernels(self._size)["cols"](self._device_grid())[0, col])

    def check_square(self, row: int, col: int, base_delay=None, interval=None, threshold=None) -> bool:
        """Check the box whose top-left corner is (row, col) — the reference
        calls this with (i*3, j*3) (reference sudoku.py:103-117, 135-137)."""
        self._tick(base_delay, interval, threshold)
        box = self._spec.box
        box_id = (row // box) * box + (col // box)
        return bool(_kernels(self._size)["boxes"](self._device_grid())[0, box_id])

    def check(self, base_delay=None, interval=None, threshold=None) -> bool:
        """Strict whole-board check (reference sudoku.py:119-140).

        The reference issues one rate-limited call per unit (9+9+9 for 9×9,
        short-circuiting on the first failure); we preserve that accounting by
        ticking the limiter per unit while validating all units in one fused
        device call.
        """
        k = _kernels(self._size)
        g = self._device_grid()
        rows = np.asarray(k["rows"](g)[0])
        cols = np.asarray(k["cols"](g)[0])
        boxes = np.asarray(k["boxes"](g)[0])
        for ok in rows:
            self._tick(base_delay, interval, threshold)
            if not ok:
                return False
        for ok in cols:
            self._tick(base_delay, interval, threshold)
            if not ok:
                return False
        for ok in boxes:
            self._tick(base_delay, interval, threshold)
            if not ok:
                return False
        return True
