"""Canonical-form answer cache (ISSUE 13).

The front-door subsystem that answers repeated puzzles — and their
symmetries — without touching the device:

  canonical.py  deterministic minimal-form reduction over the sudoku
                symmetry group's generators, producing a canonical key +
                an INVERTIBLE transform record (soundness comes from the
                transform, never from the reduction's completeness)
  store.py      sharded bounded LRU keyed by canonical hash; writes are
                gated on host-side rule verification (verified answers
                only), hits are de-canonicalized through the inverse
                transform and rule-checked before serving
  gossip.py     fleet convergence: top-K hot-set digests riding the stats
                heartbeat plus the cache_get/cache_answer UDP pair, so a
                local miss on a peer-advertised hot key fetches the
                answer instead of dispatching
"""

from .canonical import CanonicalForm, Transform, canonicalize
from .gossip import CacheGossip, PeerHotset
from .store import AnswerCache

__all__ = [
    "AnswerCache",
    "CacheGossip",
    "CanonicalForm",
    "PeerHotset",
    "Transform",
    "canonicalize",
]
