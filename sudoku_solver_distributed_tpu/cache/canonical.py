"""Symmetry canonicalization: one key per puzzle orbit, with a receipt.

Sudoku's validity-preserving symmetry group is huge (transpose ×
band/stack permutations × row/col permutations within bands/stacks ×
digit relabeling — ~3.4e9 elements at 9×9 before relabeling), which is
why an exact-match answer cache is nearly useless at the front door: the
viral puzzle arrives as thousands of *variants*, not thousands of
copies. This module reduces a board to a deterministic minimal form over
that generator set so all variants share one cache key.

The reduction is hierarchical: transpose is brute-forced (2 arms), then
bands, stacks, rows-within-bands, cols-within-stacks are each ordered by
keys that are INVARIANT under everything not yet fixed (clue-count
profiles plus global digit-frequency multisets — relabeling a digit
cannot change how often it appears), then digits are relabeled by first
occurrence. Key ties are resolved by enumerating the tied orders and
taking the lexicographically smallest final grid, bounded by
``MAX_CANDIDATES`` so an adversarial all-ties board (e.g. near-empty)
costs a constant, not a factorial. Every count/frequency table is
precomputed once per transpose arm; the enumeration loops are pure
Python tuple comparisons (the hit path must stay microseconds-cheap —
cache/store.py serves under it).

Soundness does NOT depend on the reduction being complete: every
canonicalization also returns a :class:`Transform` — the composed
(transpose, row, col, digit) permutation — and the cache proves two
boards symmetric by *applying* the transform and comparing grids, never
by trusting hash equality (cache/store.py). A missed equivalence (a tie
the bounded enumeration resolved differently on the two variants) only
costs hit rate; it can never serve a wrong answer.
"""

from __future__ import annotations

import hashlib
import itertools
import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

# bound on the tie-break search per canonicalization: orders explored at
# one level × candidates carried overall. Generic puzzles (the committed
# corpora) resolve every level with ZERO ties — the caps exist so a
# hostile near-empty board degrades to a deterministic-but-arbitrary
# representative instead of a factorial walk.
MAX_ORDERS_PER_LEVEL = 24
MAX_CANDIDATES = 64


class Transform:
    """The invertible receipt of one canonicalization.

    ``canonical[i][j] == digits[base[rows[i], cols[j]]]`` where ``base``
    is the original board transposed iff ``transposed`` and ``digits``
    maps original values → canonical values (``digits[0] == 0``: empty
    cells are never relabeled).
    """

    __slots__ = ("size", "transposed", "rows", "cols", "digits")

    def __init__(self, size, transposed, rows, cols, digits):
        self.size = int(size)
        self.transposed = bool(transposed)
        self.rows = tuple(int(r) for r in rows)
        self.cols = tuple(int(c) for c in cols)
        self.digits = tuple(int(d) for d in digits)  # len size+1, [0]==0

    def apply(self, board) -> np.ndarray:
        """Original-frame board → its canonical-frame image. The cache's
        soundness check re-applies this and compares against the stored
        canonical grid — symmetry proven by construction, not hashing."""
        arr = np.asarray(board, np.int32)
        base = arr.T if self.transposed else arr
        out = base[np.ix_(self.rows, self.cols)]
        return np.asarray(self.digits, np.int32)[out]

    def invert(self, canonical_grid) -> np.ndarray:
        """Canonical-frame grid (e.g. a cached solution) → the
        original frame. Exact inverse of :meth:`apply`."""
        arr = np.asarray(canonical_grid, np.int32)
        inv_digits = np.zeros(self.size + 1, np.int32)
        for orig, canon in enumerate(self.digits):
            inv_digits[canon] = orig
        base = np.zeros((self.size, self.size), np.int32)
        base[np.ix_(self.rows, self.cols)] = inv_digits[arr]
        return base.T if self.transposed else base


class CanonicalForm:
    """One board's canonical reduction: the minimal grid, its hash key,
    and the transform that maps the ORIGINAL board onto it."""

    __slots__ = ("grid", "key", "transform")

    def __init__(self, grid: np.ndarray, transform: Transform):
        self.grid = grid
        self.transform = transform
        self.key = grid_key(grid)


def grid_key(grid: np.ndarray) -> str:
    """The cache key of a canonical grid: size-tagged sha256 hex. One
    definition — the store, the gossip digests, and peer fetch replies
    all hash through here so keys agree across nodes byte-for-byte."""
    h = hashlib.sha256()
    h.update(b"sudoku-canon-v1:%d:" % grid.shape[0])
    h.update(np.ascontiguousarray(grid, np.int32).tobytes())
    return h.hexdigest()


def _tie_orders(keys: Sequence[tuple]) -> List[Tuple[int, ...]]:
    """All orderings of ``range(len(keys))`` that sort ``keys``
    ascending, tied items permuted — bounded at MAX_ORDERS_PER_LEVEL
    (stable order first, so truncation keeps a deterministic
    representative)."""
    order = sorted(range(len(keys)), key=lambda i: keys[i])
    groups: List[List[int]] = []
    for i in order:
        if groups and keys[groups[-1][0]] == keys[i]:
            groups[-1].append(i)
        else:
            groups.append([i])
    if all(len(g) == 1 for g in groups):
        return [tuple(order)]
    out: List[Tuple[int, ...]] = []
    for combo in itertools.product(
        *(itertools.permutations(g) for g in groups)
    ):
        out.append(tuple(i for g in combo for i in g))
        if len(out) >= MAX_ORDERS_PER_LEVEL:
            break
    return out


class _Arm:
    """Everything the enumeration needs about one transpose arm,
    precomputed with a handful of vectorized ops: per-line/per-box clue
    counts and per-line digit-frequency multisets. All keys assembled in
    the loops below are pure-Python reads of these tables."""

    __slots__ = (
        "base", "boxcnt", "rowstack", "colband", "rowtot", "coltot",
        "rowfreq", "colfreq", "bandfreq", "stackfreq",
    )

    def __init__(self, base: np.ndarray, freq: np.ndarray, b: int):
        n = b * b
        occ = (base > 0).astype(np.int32)
        self.base = base
        # per-box clue counts (band, stack)
        self.boxcnt = (
            occ.reshape(b, b, b, b).sum(axis=(1, 3)).tolist()
        )
        # per-row per-stack counts (N, b) and per-col per-band counts
        self.rowstack = occ.reshape(n, b, b).sum(axis=2).tolist()
        self.colband = occ.reshape(b, b, n).sum(axis=1).T.tolist()
        self.rowtot = occ.sum(axis=1).tolist()
        self.coltot = occ.sum(axis=0).tolist()
        # digit-frequency multisets: sorted global counts of each line's
        # clues — invariant under every permutation generator AND digit
        # relabeling (a relabel permutes digits; a multiset of their
        # global counts is blind to which digit is which). The
        # tie-breaker that makes count-profile collisions rare.
        # Vectorized: empty cells carry a sentinel ABOVE any real count,
        # so one axis-sort per table yields every line's multiset at
        # once (sentinel tails encode the clue count consistently).
        # Comparison keys stay plain lists — Python compares them
        # lexicographically exactly like tuples.
        f = np.where(base > 0, freq[base], n * n + 1)
        self.rowfreq = np.sort(f, axis=1).tolist()
        self.colfreq = np.sort(f, axis=0).T.tolist()
        self.bandfreq = np.sort(f.reshape(b, -1), axis=1).tolist()
        self.stackfreq = np.sort(
            np.ascontiguousarray(f.T).reshape(b, -1), axis=1
        ).tolist()


def canonicalize(board) -> CanonicalForm:
    """Reduce ``board`` to its canonical form. Deterministic; a handful
    of vectorized precomputes plus a bounded pure-Python enumeration.
    Raises ValueError on a non-square or non-perfect-square board."""
    arr = np.asarray(board, np.int32)
    if arr.ndim != 2 or arr.shape[0] != arr.shape[1]:
        raise ValueError(f"board must be square, got {arr.shape}")
    n = int(arr.shape[0])
    b = math.isqrt(n)
    if b * b != n:
        raise ValueError(f"board edge {n} is not a perfect square")
    if arr.size and (int(arr.min()) < 0 or int(arr.max()) > n):
        # out-of-range cells must raise the ValueError every caller
        # catches — NOT index into the relabel table (a hostile
        # cache_answer board with a -999 cell raised IndexError out of
        # the UDP loop; small negatives aliased digits silently)
        raise ValueError(f"cell values must be in 0..{n}")

    # global digit frequencies are transpose/permutation-invariant: one
    # computation serves both arms and every candidate
    freq = np.bincount(arr.ravel(), minlength=n + 1)

    best: Optional[Tuple[bytes, np.ndarray, Transform]] = None
    candidates = 0
    rng_b = range(b)

    for transposed in (False, True):
        arm = _Arm(arr.T if transposed else arr, freq, b)

        # -- bands: key invariant under stack perms + inner perms +
        #    relabel = (sorted per-box counts, sorted per-row counts,
        #    band digit-frequency multiset) ---------------------------
        band_keys = [
            (
                tuple(sorted(arm.boxcnt[g])),
                tuple(sorted(arm.rowtot[g * b : g * b + b])),
                arm.bandfreq[g],
            )
            for g in rng_b
        ]
        for band_order in _tie_orders(band_keys):
            # -- stacks: band order now fixed, so per-box counts are an
            #    ORDERED tuple over bands (stronger than sorted) -------
            stack_keys = [
                (
                    tuple(arm.boxcnt[g][s] for g in band_order),
                    tuple(sorted(arm.coltot[s * b : s * b + b])),
                    arm.stackfreq[s],
                )
                for s in rng_b
            ]
            for stack_order in _tie_orders(stack_keys):
                # -- rows within each band: per-stack counts in the
                #    now-canonical stack order + frequency multiset ----
                per_band_orders = []
                for g in band_order:
                    keys = []
                    for i in rng_b:
                        r = g * b + i
                        rs = arm.rowstack[r]
                        keys.append(
                            (
                                tuple(rs[s] for s in stack_order),
                                arm.rowfreq[r],
                            )
                        )
                    per_band_orders.append(_tie_orders(keys))
                # -- cols within each stack (independent of the row
                #    choice: per-band counts only see band MEMBERSHIP,
                #    which in-band row perms never change) -------------
                per_stack_orders = []
                for s in stack_order:
                    keys = []
                    for j in rng_b:
                        c = s * b + j
                        cb = arm.colband[c]
                        keys.append(
                            (
                                tuple(cb[g] for g in band_order),
                                arm.colfreq[c],
                            )
                        )
                    per_stack_orders.append(_tie_orders(keys))

                for row_choice in itertools.islice(
                    itertools.product(*per_band_orders),
                    MAX_ORDERS_PER_LEVEL,
                ):
                    rows_final = [
                        g * b + i
                        for g, order in zip(band_order, row_choice)
                        for i in order
                    ]
                    for col_choice in itertools.islice(
                        itertools.product(*per_stack_orders),
                        MAX_ORDERS_PER_LEVEL,
                    ):
                        cols_final = [
                            s * b + j
                            for s, order in zip(
                                stack_order, col_choice
                            )
                            for j in order
                        ]
                        g4 = arm.base[np.ix_(rows_final, cols_final)]

                        # -- digit relabeling: first occurrence, row-
                        #    major over the now-fixed cell order -------
                        digits = [0] * (n + 1)
                        next_label = 1
                        for v in g4.ravel().tolist():
                            if v and digits[v] == 0:
                                digits[v] = next_label
                                next_label += 1
                        for v in range(1, n + 1):
                            # unused digits keep the transform a true
                            # permutation of 1..N
                            if digits[v] == 0:
                                digits[v] = next_label
                                next_label += 1
                        dig = np.asarray(digits, np.int32)
                        g5 = dig[g4]

                        key_bytes = g5.tobytes()
                        if best is None or key_bytes < best[0]:
                            best = (
                                key_bytes,
                                g5,
                                Transform(
                                    n, transposed, rows_final,
                                    cols_final, digits,
                                ),
                            )
                        candidates += 1
                        if candidates >= MAX_CANDIDATES:
                            return CanonicalForm(best[1], best[2])
    assert best is not None  # the loops always emit ≥1 candidate
    return CanonicalForm(best[1], best[2])


def random_symmetry(board, rng: np.random.Generator) -> List[List[int]]:
    """Apply a uniformly sampled element of the documented generator set
    (transpose × band perm × stack perm × in-band row perms × in-stack
    col perms × digit relabeling) — the test/bench utility that
    manufactures 'the same viral puzzle, differently dressed'."""
    arr = np.asarray(board, np.int32)
    n = arr.shape[0]
    b = math.isqrt(n)
    if rng.integers(2):
        arr = arr.T.copy()
    band_perm = rng.permutation(b)
    rows = np.concatenate(
        [np.arange(g * b, g * b + b)[rng.permutation(b)] for g in band_perm]
    )
    stack_perm = rng.permutation(b)
    cols = np.concatenate(
        [np.arange(s * b, s * b + b)[rng.permutation(b)] for s in stack_perm]
    )
    relabel = np.concatenate(
        [[0], rng.permutation(np.arange(1, n + 1))]
    ).astype(np.int32)
    return relabel[arr[np.ix_(rows, cols)]].tolist()
