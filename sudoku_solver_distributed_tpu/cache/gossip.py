"""Fleet cache convergence: hot-set gossip + peer answer fetch.

The cluster layer of the answer cache (ISSUE 13 tentpole 3). Two wire
surfaces, both speaking the existing UDP protocol's idioms:

  * **hot-set digest** — each node's top-K canonical hashes (+ hit
    counts) ride the 1 Hz stats heartbeat as an optional trailing
    ``hotset`` key (net/wire.stats_msg — the PR 5/10 variant pattern;
    absent key keeps reference traffic byte-identical). Peers fold the
    digest into a TTL'd, bounded, ingress-sanitized map
    (:class:`PeerHotset`) — evidence, not membership, exactly like
    PeerHealth/PeerTelemetry.
  * **cache_get / cache_answer** — a node that MISSES locally on a key
    some fresh peer advertises sends ``cache_get`` and waits a bounded
    beat for the ``cache_answer`` carrying the canonical (board,
    solution) pair. The answer is verified on arrival through the
    store's write gate (cache/store.py ``store_canonical``: re-hashed
    under OUR canonicalization, rule-checked host-side), so a hostile or
    corrupt peer answer is counted and dropped, never served. The fetch
    replaces a device dispatch; a timeout just falls through to the
    normal solve path.

Net effect: one node solves the viral puzzle, every node answers its
whole symmetry orbit from cache within a gossip interval.
"""

from __future__ import annotations

import logging
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# a canonical key is a 64-char lowercase sha256 hex digest — the ingress
# shape gate for every wire-carried hash field
_KEY_RE = re.compile(r"^[0-9a-f]{64}$")

DIGEST_VERSION = 1


def valid_key(raw) -> Optional[str]:
    """Wire-ingress validation of a canonical hash; None when malformed."""
    if isinstance(raw, str) and _KEY_RE.fullmatch(raw):
        return raw
    return None


class PeerHotset:
    """Last-known hot-set digest per peer, carried by the ``hotset``
    piggyback on stats gossip. Same evidence-not-membership contract as
    net/stats.PeerHealth: entries EXPIRE (``ttl_s``), departures forget
    the peer, and both the peer count and the keys-per-peer are bounded
    with full ingress sanitization — a hostile datagram can neither grow
    the heap nor plant garbage keys."""

    MAX_ENTRIES = 256   # peers tracked (flood bound, same as PeerHealth)
    MAX_KEYS = 32       # hot keys accepted per peer digest

    def __init__(self, ttl_s: float = 15.0):
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        # peer -> (frozenset of keys, {key: hits}, monotonic receive t)
        self._sets: Dict[str, tuple] = {}

    @classmethod
    def sanitize(cls, raw) -> Optional[Dict[str, int]]:
        """{"v": 1, "keys": [[hex, hits], ...]} → {hex: hits}, or None.
        Rejected whole on any malformed element — partial acceptance
        would let one valid key smuggle junk siblings in."""
        if not isinstance(raw, dict):
            return None
        keys = raw.get("keys")
        if not isinstance(keys, list) or len(keys) > cls.MAX_KEYS:
            return None
        out: Dict[str, int] = {}
        for item in keys:
            if not isinstance(item, (list, tuple)) or len(item) != 2:
                return None
            key, hits = item
            if valid_key(key) is None:
                return None
            if not isinstance(hits, int) or isinstance(hits, bool) or (
                not 0 <= hits < 1 << 31
            ):
                # an absurd claimed count is a lie, and lies rank fetch
                # targets (holders sorts hottest-first) — rejected
                # whole like every other malformed digest
                return None
            out[key] = hits
        return out

    def _purge_locked(self, now: float) -> None:
        """(lock held) Drop expired digests — the ONE expiry rule every
        reader applies, so holders() can never offer a fetch target
        snapshot() already considers dead."""
        for p in [
            p
            for p, (_, _, t) in self._sets.items()
            if now - t > self.ttl_s
        ]:
            del self._sets[p]

    def note(self, peer: str, raw) -> None:
        digest = self.sanitize(raw)
        if digest is None:
            return
        now = time.monotonic()
        with self._lock:
            self._sets[peer] = (frozenset(digest), digest, now)
            if len(self._sets) > self.MAX_ENTRIES:
                self._purge_locked(now)
            while len(self._sets) > self.MAX_ENTRIES:
                oldest = min(
                    self._sets.items(), key=lambda kv: kv[1][2]
                )
                del self._sets[oldest[0]]

    def holders(self, key: str) -> List[str]:
        """Peers whose FRESH (unexpired) digest advertises ``key``,
        hottest-first (the advertised hit count ranks fetch targets: a
        peer serving the key thousands of times is the likeliest to
        still hold it and the least bothered by one more get)."""
        now = time.monotonic()
        with self._lock:
            self._purge_locked(now)
            matches = [
                (p, hits.get(key, 0))
                for p, (keys, hits, _) in self._sets.items()
                if key in keys
            ]
        matches.sort(key=lambda ph: -ph[1])
        return [p for p, _ in matches]

    def forget(self, peer: str) -> None:
        with self._lock:
            self._sets.pop(peer, None)

    def snapshot(self) -> Dict[str, dict]:
        now = time.monotonic()
        with self._lock:
            self._purge_locked(now)
            return {
                p: {"age_s": round(now - t, 3), "keys": len(keys)}
                for p, (keys, _, t) in self._sets.items()
            }


class CacheGossip:
    """One node's cache-convergence plane: builds the outgoing hot-set
    digest (cached between heartbeats, like obs/cluster's publisher),
    folds peers' digests, answers ``cache_get``, verifies
    ``cache_answer``, and runs the bounded blocking fetch the front door
    calls on a peer-hot miss.

    Args:
      cache: the node's AnswerCache.
      node: the owning P2PNode (send surface + identity).
      top_k: hot-set size gossiped per heartbeat.
      fetch_timeout_s: how long a miss waits for a peer answer before
        falling through to the normal solve path. Bounded and small on
        purpose: the fallback is not an error, it is the device doing
        its job.
      fanout: peers asked per fetch (first answer wins; the rest are
        idempotent folds).
      max_concurrent_fetches: handler threads allowed to be parked in
        ``try_peer_fetch`` at once. The fetch runs BEFORE admission (a
        hot key must be answerable even when the backlog would shed),
        so without a bound a burst of misses on stale-advertised keys
        could park the whole transport worker pool for a fetch-timeout
        each; at the cap a miss just dispatches normally.
    """

    def __init__(
        self,
        cache,
        node,
        *,
        top_k: int = 16,
        ttl_s: float = 15.0,
        fetch_timeout_s: float = 0.25,
        fanout: int = 2,
        min_interval_s: float = 1.0,
        max_concurrent_fetches: int = 8,
    ):
        self.cache = cache
        self.node = node
        self.top_k = int(top_k)
        self.fetch_timeout_s = float(fetch_timeout_s)
        self.fanout = max(1, int(fanout))
        self.peers = PeerHotset(ttl_s=ttl_s)
        self.min_interval_s = min_interval_s
        self.max_concurrent_fetches = max(1, int(max_concurrent_fetches))
        self._fetching = 0  # parked fetchers (under _waiters_lock)
        self.fetches_capped = 0  # misses that skipped the fetch at cap
        self.unsolicited_answers = 0  # answers dropped, no fetch waiting
        self._fetch_rotation = 0  # round-robin over non-top holders
        self._digest_lock = threading.Lock()
        self._cached_digest: Optional[dict] = None
        self._cached_at = 0.0
        # key -> (threading.Event, waiter count); signaled by
        # on_cache_answer after a verified fold lands under that key
        self._waiters: Dict[str, Tuple[threading.Event, int]] = {}
        self._waiters_lock = threading.Lock()
        self.peer_serves = 0  # cache_get datagrams answered (benign race)

    # -- outgoing digest ---------------------------------------------------
    def digest(self) -> Optional[dict]:
        """The ``hotset`` payload for the next stats heartbeat, rebuilt
        at most once per ``min_interval_s`` (broadcast_stats runs once
        per /solve on the serving path); None — key absent on the wire —
        while the cache is empty."""
        now = time.monotonic()
        with self._digest_lock:
            if (
                self._cached_digest is not None
                and now - self._cached_at < self.min_interval_s
            ):
                return self._cached_digest or None
            hot = self.cache.hot_set(self.top_k)
            self._cached_digest = (
                {"v": DIGEST_VERSION, "keys": [[k, h] for k, h in hot]}
                if hot
                else {}
            )
            self._cached_at = now
            return self._cached_digest or None

    # -- ingress (UDP loop thread, net/node.py) ----------------------------
    def note_hotset(self, peer: str, raw) -> None:
        self.peers.note(peer, raw)

    def on_cache_get(self, msg, source=None) -> None:
        """Answer a peer's fetch from our store; unknown keys are
        silently ignored (the peer's timeout is the negative reply —
        a 'not found' datagram would only invite spoofed floods).

        Reflection guard: the multi-KB positive reply goes to the
        claimed ``address`` only when it matches the datagram's UDP
        ``source`` (wire.same_endpoint — nodes send from their bound
        socket, the same identity rule goodbyes use). Without the
        check, a ~120-byte spoofed get for a gossip-advertised hot key
        would reflect a 15-30× larger cache_answer at any victim."""
        from ..net import wire

        key = valid_key(msg["hash"])
        if key is None:
            return
        if source is not None:
            try:
                claimed = wire.parse_address(msg["address"])
            except (ValueError, TypeError):
                return
            if not wire.same_endpoint(tuple(source[:2]), claimed):
                logger.warning(
                    "dropping cache_get whose address %r does not "
                    "match its source %r", msg["address"], source,
                )
                return
        pair = self.cache.get_canonical(key)
        if pair is None:
            return
        board, solution = pair
        self.node.send_to(
            msg["address"],
            wire.cache_answer_msg(key, board, solution, self.node.id),
        )
        self.peer_serves += 1

    def on_cache_answer(self, msg) -> None:
        """Fold a peer's answer through the store's write gate, then
        wake the fetch waiting on that key. The claimed hash is never
        trusted: store_canonical re-canonicalizes the carried board, so
        the entry lands under the key WE compute — the waiter's
        post-wake ``contains`` check closes the loop.

        SOLICITED answers only: a datagram for a key no fetch is
        waiting on is dropped before any verification runs. Without the
        gate, an attacker streaming valid-but-unsolicited (board,
        solution) pairs — trivial to mint from any complete grid —
        would both flush the genuine hot set through the per-shard LRU
        and burn ~0.5 ms of canonicalize+verify on the UDP ingress
        thread per datagram, starving heartbeat/membership processing.
        Waiters register BEFORE the gets go out (try_peer_fetch), so a
        legitimate answer always finds its waiter; late answers after
        the timeout are dropped like any other unsolicited datagram
        (the asking node will re-fetch or has already dispatched)."""
        key = valid_key(msg["hash"])
        if key is None:
            return
        with self._waiters_lock:
            entry = self._waiters.get(key)
        if entry is None:
            self.unsolicited_answers += 1  # benign-race counter
            return
        if not self.cache.store_canonical(msg["board"], msg["solution"]):
            return
        entry[0].set()

    # -- the front door's fetch (handler thread) ---------------------------
    def try_peer_fetch(self, key: str, timeout_s=None) -> bool:
        """On a local miss: if any fresh peer advertises ``key``, ask up
        to ``fanout`` of them and wait (bounded) for a verified answer
        to land. True iff the cache now holds the key — the caller
        re-runs its lookup and serves the hit.

        ``timeout_s`` caps the wait BELOW the configured fetch timeout
        (never above): the front door passes the request's remaining
        deadline budget, so a 50 ms-budget request never parks 250 ms
        for an answer it could no longer use."""
        wait_s = self.fetch_timeout_s
        if timeout_s is not None:
            wait_s = min(wait_s, timeout_s)
        if wait_s <= 0:
            return False  # disabled (CLI timeout 0) or budget spent
        holders = self.peers.holders(key)
        if not holders:
            return False
        from ..net import wire

        with self._waiters_lock:
            if self._fetching >= self.max_concurrent_fetches:
                # the park budget is spent: this miss dispatches
                # normally instead of joining a pile-up that could
                # exhaust the transport worker pool pre-admission
                self.fetches_capped += 1
                return False
            self._fetching += 1
            ev, count = self._waiters.get(key, (threading.Event(), 0))
            self._waiters[key] = (ev, count + 1)
        try:
            self.cache._count("peer_fetches")
            msg = wire.cache_get_msg(key, self.node.id)
            # top-(fanout−1) hottest holders plus ONE rotated from the
            # rest: a pair of hostile peers advertising inflated counts
            # can then monopolize at most fanout−1 slots — an honest
            # holder is still asked within len(holders) fetches
            targets = holders[: max(1, self.fanout - 1)]
            rest = holders[len(targets):]
            if rest and len(targets) < self.fanout:
                self._fetch_rotation += 1
                targets.append(rest[self._fetch_rotation % len(rest)])
            for peer in targets:
                self.node.send_to(peer, msg)
            ev.wait(wait_s)
        finally:
            with self._waiters_lock:
                self._fetching -= 1
                ev2, count2 = self._waiters.get(key, (ev, 1))
                if count2 <= 1:
                    self._waiters.pop(key, None)
                else:
                    self._waiters[key] = (ev2, count2 - 1)
        return self.cache.contains(key)

    def forget(self, peer: str) -> None:
        """A departed peer's advertisements die with it."""
        self.peers.forget(peer)

    def snapshot(self) -> dict:
        """The gossip half of the ``engine.cost.cache`` metrics block —
        scalar gauges only (the block flattens into Prometheus names;
        per-peer detail lives on ``peers.snapshot()`` for tests/debug)."""
        return {
            "peers_advertising": len(self.peers.snapshot()),
            "peer_serves": self.peer_serves,
            "fetches_capped": self.fetches_capped,
            "unsolicited_answers": self.unsolicited_answers,
            "top_k": self.top_k,
            "fetch_timeout_ms": round(self.fetch_timeout_s * 1e3, 1),
        }
