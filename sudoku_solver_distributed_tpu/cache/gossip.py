"""Fleet cache convergence: hot-set gossip + peer answer fetch.

The cluster layer of the answer cache (ISSUE 13 tentpole 3). Two wire
surfaces, both speaking the existing UDP protocol's idioms:

  * **hot-set digest** — each node's top-K canonical hashes (+ hit
    counts) ride the 1 Hz stats heartbeat as an optional trailing
    ``hotset`` key (net/wire.stats_msg — the PR 5/10 variant pattern;
    absent key keeps reference traffic byte-identical). Peers fold the
    digest into a TTL'd, bounded, ingress-sanitized map
    (:class:`PeerHotset`) — evidence, not membership, exactly like
    PeerHealth/PeerTelemetry.
  * **cache_get / cache_answer** — a node that MISSES locally on a key
    some fresh peer advertises sends ``cache_get`` and waits a bounded
    beat for the ``cache_answer`` carrying the canonical (board,
    solution) pair. The UDP ingress thread only DELIVERS the payload to
    the parked fetcher (bounded append + event set — the receive loop
    never canonicalizes, THREAD101); the fetcher thread verifies it
    through the store's write gate (cache/store.py ``store_canonical``:
    re-hashed under OUR canonicalization, rule-checked host-side), so a
    hostile or corrupt peer answer is counted and dropped, never
    served. The fetch replaces a device dispatch; a timeout just falls
    through to the normal solve path.

Net effect: one node solves the viral puzzle, every node answers its
whole symmetry orbit from cache within a gossip interval.
"""

from __future__ import annotations

import logging
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..net.peermap import PeerMap

logger = logging.getLogger(__name__)

# a canonical key is a 64-char lowercase sha256 hex digest — the ingress
# shape gate for every wire-carried hash field
_KEY_RE = re.compile(r"^[0-9a-f]{64}$")

DIGEST_VERSION = 1


def valid_key(raw) -> Optional[str]:
    """Wire-ingress validation of a canonical hash; None when malformed."""
    if isinstance(raw, str) and _KEY_RE.fullmatch(raw):
        return raw
    return None


class PeerHotset(PeerMap):
    """Last-known hot-set digest per peer, carried by the ``hotset``
    piggyback on stats gossip. Same evidence-not-membership contract as
    net/stats.PeerHealth, via the shared base (net/peermap.PeerMap,
    ISSUE 14): entries EXPIRE (``ttl_s``) — so holders() can never offer
    a fetch target snapshot() already considers dead — departures forget
    the peer, and both the peer count and the keys-per-peer are bounded
    with full ingress sanitization: a hostile datagram can neither grow
    the heap nor plant garbage keys."""

    MAX_KEYS = 32       # hot keys accepted per peer digest

    @classmethod
    def sanitize(cls, raw) -> Optional[Dict[str, int]]:
        """{"v": 1, "keys": [[hex, hits], ...]} → {hex: hits}, or None.
        Rejected whole on any malformed element — partial acceptance
        would let one valid key smuggle junk siblings in."""
        if not isinstance(raw, dict):
            return None
        keys = raw.get("keys")
        if not isinstance(keys, list) or len(keys) > cls.MAX_KEYS:
            return None
        out: Dict[str, int] = {}
        for item in keys:
            if not isinstance(item, (list, tuple)) or len(item) != 2:
                return None
            key, hits = item
            if valid_key(key) is None:
                return None
            if not isinstance(hits, int) or isinstance(hits, bool) or (
                not 0 <= hits < 1 << 31
            ):
                # an absurd claimed count is a lie, and lies rank fetch
                # targets (holders sorts hottest-first) — rejected
                # whole like every other malformed digest
                return None
            out[key] = hits
        return out

    def holders(self, key: str) -> List[str]:
        """Peers whose FRESH (unexpired) digest advertises ``key``,
        hottest-first (the advertised hit count ranks fetch targets: a
        peer serving the key thousands of times is the likeliest to
        still hold it and the least bothered by one more get)."""
        matches = [
            (p, hits.get(key, 0))
            for p, (hits, _age) in self.items().items()
            if key in hits
        ]
        matches.sort(key=lambda ph: -ph[1])
        return [p for p, _ in matches]

    def advertised(self) -> Dict[str, Dict[str, int]]:
        """Every FRESH advertisement: {peer: {key: hits}} — the joiner
        prewarm's shopping list (CacheGossip.prewarm, ISSUE 14)."""
        return {p: dict(hits) for p, (hits, _age) in self.items().items()}

    def snapshot(self) -> Dict[str, dict]:
        return {
            p: {"age_s": round(age, 3), "keys": len(hits)}
            for p, (hits, age) in self.items().items()
        }


class _Waiter:
    """One key's parked fetchers: the wake event, how many threads are
    registered on it, and the raw answer payloads delivered by the UDP
    loop awaiting verification on a fetcher thread. Payloads are capped:
    a flood of answers for a solicited key can park at most
    ``MAX_PAYLOADS`` boards here, not grow the heap."""

    MAX_PAYLOADS = 4

    __slots__ = ("event", "count", "payloads")

    def __init__(self):
        self.event = threading.Event()
        self.count = 0
        self.payloads: List[Tuple[object, object]] = []


class CacheGossip:
    """One node's cache-convergence plane: builds the outgoing hot-set
    digest (cached between heartbeats, like obs/cluster's publisher),
    folds peers' digests, answers ``cache_get``, verifies
    ``cache_answer``, and runs the bounded blocking fetch the front door
    calls on a peer-hot miss.

    Args:
      cache: the node's AnswerCache.
      node: the owning P2PNode (send surface + identity).
      top_k: hot-set size gossiped per heartbeat.
      fetch_timeout_s: how long a miss waits for a peer answer before
        falling through to the normal solve path. Bounded and small on
        purpose: the fallback is not an error, it is the device doing
        its job.
      fanout: peers asked per fetch (first answer wins; the rest are
        idempotent folds).
      max_concurrent_fetches: handler threads allowed to be parked in
        ``try_peer_fetch`` at once. The fetch runs BEFORE admission (a
        hot key must be answerable even when the backlog would shed),
        so without a bound a burst of misses on stale-advertised keys
        could park the whole transport worker pool for a fetch-timeout
        each; at the cap a miss just dispatches normally.
    """

    def __init__(
        self,
        cache,
        node,
        *,
        top_k: int = 16,
        ttl_s: float = 15.0,
        fetch_timeout_s: float = 0.25,
        fanout: int = 2,
        min_interval_s: float = 1.0,
        max_concurrent_fetches: int = 8,
    ):
        self.cache = cache
        self.node = node
        self.top_k = int(top_k)
        self.fetch_timeout_s = float(fetch_timeout_s)
        self.fanout = max(1, int(fanout))
        self.peers = PeerHotset(ttl_s=ttl_s)
        self.min_interval_s = min_interval_s
        self.max_concurrent_fetches = max(1, int(max_concurrent_fetches))
        self._fetching = 0  # parked fetchers (under _waiters_lock)
        self.fetches_capped = 0  # misses that skipped the fetch at cap
        self.unsolicited_answers = 0  # answers dropped, no fetch waiting
        self._fetch_rotation = 0  # round-robin over non-top holders
        # joiner prewarm counters (ISSUE 14 — see prewarm())
        self.prewarm_runs = 0
        self.prewarm_requested = 0
        self.prewarm_landed = 0
        self._digest_lock = threading.Lock()
        self._cached_digest: Optional[dict] = None
        self._cached_at = 0.0
        # key -> _Waiter; on_cache_answer appends the RAW payload and
        # signals — the waiting fetcher thread verifies (the UDP loop
        # must never canonicalize)
        self._waiters: Dict[str, _Waiter] = {}
        self._waiters_lock = threading.Lock()
        self.peer_serves = 0  # cache_get datagrams answered (benign race)

    # -- outgoing digest ---------------------------------------------------
    def digest(self) -> Optional[dict]:
        """The ``hotset`` payload for the next stats heartbeat, rebuilt
        at most once per ``min_interval_s`` (broadcast_stats runs once
        per /solve on the serving path); None — key absent on the wire —
        while the cache is empty."""
        now = time.monotonic()
        with self._digest_lock:
            if (
                self._cached_digest is not None
                and now - self._cached_at < self.min_interval_s
            ):
                return self._cached_digest or None
            hot = self.cache.hot_set(self.top_k)
            self._cached_digest = (
                {"v": DIGEST_VERSION, "keys": [[k, h] for k, h in hot]}
                if hot
                else {}
            )
            self._cached_at = now
            return self._cached_digest or None

    # -- ingress (UDP loop thread, net/node.py) ----------------------------
    def note_hotset(self, peer: str, raw) -> None:
        self.peers.note(peer, raw)

    def on_cache_get(self, msg, source=None) -> None:
        """Answer a peer's fetch from our store; unknown keys are
        silently ignored (the peer's timeout is the negative reply —
        a 'not found' datagram would only invite spoofed floods).

        Reflection guard: the multi-KB positive reply goes to the
        claimed ``address`` only when it matches the datagram's UDP
        ``source`` (wire.same_endpoint — nodes send from their bound
        socket, the same identity rule goodbyes use). Without the
        check, a ~120-byte spoofed get for a gossip-advertised hot key
        would reflect a 15-30× larger cache_answer at any victim."""
        from ..net import wire

        key = valid_key(msg["hash"])
        if key is None:
            return
        if source is not None:
            try:
                claimed = wire.parse_address(msg["address"])
            except (ValueError, TypeError):
                return
            if not wire.same_endpoint(tuple(source[:2]), claimed):
                logger.warning(
                    "dropping cache_get whose address %r does not "
                    "match its source %r", msg["address"], source,
                )
                return
        pair = self.cache.get_canonical(key)
        if pair is None:
            return
        board, solution = pair
        self.node.send_to(
            msg["address"],
            wire.cache_answer_msg(key, board, solution, self.node.id),
        )
        self.peer_serves += 1

    def on_cache_answer(self, msg) -> None:
        """Deliver a peer's answer to the fetch parked on that key and
        wake it. This runs on the UDP receive loop, so it does ONLY
        O(1) work — a bounded payload append and an event set; the
        woken fetcher thread runs the store's write gate
        (``_verify_delivered`` → store_canonical), where the claimed
        hash is never trusted: the carried board is re-canonicalized so
        the entry lands under the key WE compute, and the waiter's
        post-verify ``contains`` check closes the loop.

        SOLICITED answers only: a datagram for a key no fetch is
        waiting on is dropped on arrival. Without the gate, an attacker
        streaming valid-but-unsolicited (board, solution) pairs —
        trivial to mint from any complete grid — would flush the
        genuine hot set through the per-shard LRU; the delivery cap
        (``_Waiter.MAX_PAYLOADS``) bounds what a flood on a SOLICITED
        key can park. Waiters register BEFORE the gets go out
        (try_peer_fetch), so a legitimate answer always finds its
        waiter; late answers after the timeout are dropped like any
        other unsolicited datagram (the asking node will re-fetch or
        has already dispatched)."""
        key = valid_key(msg["hash"])
        if key is None:
            return
        board, solution = msg["board"], msg["solution"]
        with self._waiters_lock:
            entry = self._waiters.get(key)
            if entry is None:
                self.unsolicited_answers += 1  # benign-race counter
                return
            if len(entry.payloads) < _Waiter.MAX_PAYLOADS:
                entry.payloads.append((board, solution))
            entry.event.set()

    # -- waiter bookkeeping (fetcher threads) ------------------------------
    def _register_waiter(self, key: str) -> _Waiter:
        """Caller holds ``_waiters_lock``."""
        entry = self._waiters.get(key)
        if entry is None:
            entry = self._waiters[key] = _Waiter()
        entry.count += 1
        return entry

    def _release_waiter(self, key: str) -> None:
        """Drop one registration; the last one out verifies any
        payloads still parked (an answer that raced the timeout should
        still land for the NEXT request) and removes the entry."""
        self._verify_delivered(key)
        with self._waiters_lock:
            entry = self._waiters.get(key)
            if entry is None:
                return
            entry.count -= 1
            if entry.count <= 0:
                self._waiters.pop(key, None)

    def _verify_delivered(self, key: str) -> bool:
        """Run delivered payloads through the store's write gate — on
        the CALLING (fetcher) thread, never the UDP loop. True iff a
        payload verified and landed."""
        while True:
            with self._waiters_lock:
                entry = self._waiters.get(key)
                if entry is None or not entry.payloads:
                    return False
                board, solution = entry.payloads.pop(0)
            if self.cache.store_canonical(board, solution):
                return True

    # -- the front door's fetch (handler thread) ---------------------------
    def try_peer_fetch(self, key: str, timeout_s=None) -> bool:
        """On a local miss: if any fresh peer advertises ``key``, ask up
        to ``fanout`` of them and wait (bounded) for a verified answer
        to land. True iff the cache now holds the key — the caller
        re-runs its lookup and serves the hit.

        ``timeout_s`` caps the wait BELOW the configured fetch timeout
        (never above): the front door passes the request's remaining
        deadline budget, so a 50 ms-budget request never parks 250 ms
        for an answer it could no longer use."""
        wait_s = self.fetch_timeout_s
        if timeout_s is not None:
            wait_s = min(wait_s, timeout_s)
        if wait_s <= 0:
            return False  # disabled (CLI timeout 0) or budget spent
        holders = self.peers.holders(key)
        if not holders:
            return False
        from ..net import wire

        with self._waiters_lock:
            if self._fetching >= self.max_concurrent_fetches:
                # the park budget is spent: this miss dispatches
                # normally instead of joining a pile-up that could
                # exhaust the transport worker pool pre-admission
                self.fetches_capped += 1
                return False
            self._fetching += 1
            entry = self._register_waiter(key)
        try:
            self.cache._count("peer_fetches")
            msg = wire.cache_get_msg(key, self.node.id)
            # top-(fanout−1) hottest holders plus ONE rotated from the
            # rest: a pair of hostile peers advertising inflated counts
            # can then monopolize at most fanout−1 slots — an honest
            # holder is still asked within len(holders) fetches
            targets = holders[: max(1, self.fanout - 1)]
            rest = holders[len(targets):]
            if rest and len(targets) < self.fanout:
                self._fetch_rotation += 1
                targets.append(rest[self._fetch_rotation % len(rest)])
            for peer in targets:
                self.node.send_to(peer, msg)
            deadline = time.monotonic() + wait_s
            while not self.cache.contains(key):
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not entry.event.wait(remaining):
                    break  # budget spent with no delivery
                if self._verify_delivered(key):
                    break  # verified fold landed under our key
                # a hostile/corrupt answer must not end the wait early:
                # re-arm and keep waiting for an honest one — unless a
                # further delivery raced in while we were verifying
                with self._waiters_lock:
                    if not entry.payloads:
                        entry.event.clear()
        finally:
            with self._waiters_lock:
                self._fetching -= 1
            self._release_waiter(key)
        return self.cache.contains(key)

    # -- joiner prewarm (ISSUE 14 satellite) -------------------------------
    def prewarm(
        self,
        *,
        max_keys: int = 64,
        budget_s: float = 2.0,
        per_peer: int = 16,
    ) -> Tuple[int, int]:
        """Bulk-fetch peers' advertised hot sets on join, instead of
        converging one front-door miss at a time (PR 13's recorded
        remaining edge — the natural partner of elastic membership: a
        node that defers gossip advertisement until it is servable
        should arrive already holding the fleet's viral answers).

        Bounded on every axis: at most ``max_keys`` keys total (the
        hottest advertised keys we don't already hold), at most
        ``per_peer`` gets sent to any one holder, and one total
        ``budget_s`` wall-clock wait for the whole run. Every reply
        folds through the store's verified write gate exactly like a
        front-door fetch (on_cache_answer → store_canonical → _admit:
        re-canonicalized under OUR key, rule-verified host-side), so a
        hostile peer can poison nothing — a bad answer is counted and
        dropped, and the key simply stays cold.

        Returns (requested, landed). Idempotent and safe to call again
        (e.g. after a partition heals); the autopilot's membership loop
        runs it once per join (serving/autopilot.py).
        """
        t_end = time.monotonic() + max(0.0, budget_s)
        adv = self.peers.advertised()
        score: Dict[str, int] = {}
        holders: Dict[str, List[str]] = {}
        for peer, keys in adv.items():
            for k, h in keys.items():
                if self.cache.contains(k):
                    continue
                score[k] = max(score.get(k, 0), h)
                holders.setdefault(k, []).append(peer)
        wanted = sorted(score, key=lambda k: (-score[k], k))[
            : max(0, int(max_keys))
        ]
        self.prewarm_runs += 1
        if not wanted:
            return 0, 0
        from ..net import wire

        # register every waiter BEFORE any get goes out (the solicited-
        # answers gate in on_cache_answer) — same discipline as
        # try_peer_fetch, shared waiter table
        entries = {}
        with self._waiters_lock:
            for k in wanted:
                entries[k] = self._register_waiter(k)
        sent_per_peer: Dict[str, int] = {}
        try:
            asked = []
            for k in wanted:
                # hottest holder first, skipping peers already at their
                # per-peer budget — an advertised-everywhere key must
                # not concentrate the whole run on one node
                target = None
                ranked = sorted(
                    holders[k],
                    key=lambda p: (-adv[p].get(k, 0), p),
                )
                for p in ranked:
                    if sent_per_peer.get(p, 0) < per_peer:
                        target = p
                        break
                if target is None:
                    continue
                sent_per_peer[target] = sent_per_peer.get(target, 0) + 1
                self.node.send_to(
                    target, wire.cache_get_msg(k, self.node.id)
                )
                asked.append(k)
            self.prewarm_requested += len(asked)
            for k in asked:
                remaining = t_end - time.monotonic()
                if remaining <= 0:
                    break
                if entries[k].event.wait(remaining):
                    # fold the delivery on THIS thread; the UDP loop
                    # only parked the raw payload
                    self._verify_delivered(k)
        finally:
            for k in wanted:
                # _release_waiter drains any answer that raced the
                # budget before dropping the registration
                self._release_waiter(k)
        landed = sum(1 for k in wanted if self.cache.contains(k))
        self.prewarm_landed += landed
        return len(wanted), landed

    def forget(self, peer: str) -> None:
        """A departed peer's advertisements die with it."""
        self.peers.forget(peer)

    def snapshot(self) -> dict:
        """The gossip half of the ``engine.cost.cache`` metrics block —
        scalar gauges only (the block flattens into Prometheus names;
        per-peer detail lives on ``peers.snapshot()`` for tests/debug)."""
        return {
            "peers_advertising": len(self.peers.snapshot()),
            "peer_serves": self.peer_serves,
            "fetches_capped": self.fetches_capped,
            "unsolicited_answers": self.unsolicited_answers,
            "top_k": self.top_k,
            "fetch_timeout_ms": round(self.fetch_timeout_s * 1e3, 1),
            # joiner prewarm (ISSUE 14): bulk hot-set fetch on join
            "prewarm_runs": self.prewarm_runs,
            "prewarm_requested": self.prewarm_requested,
            "prewarm_landed": self.prewarm_landed,
        }
