"""Verified canonical-form answer store: sharded, bounded, poison-proof.

The LRU behind the front door (net/http_api.py). Entries are keyed by
the canonical hash (cache/canonical.py) and hold the CANONICAL board +
CANONICAL solution pair, so one entry serves the puzzle's whole symmetry
orbit: a hit de-canonicalizes the stored solution back through the
requester's own inverse transform.

Two verification gates make cache poisoning impossible by construction:

  * **write gate** — ``store`` re-verifies every candidate answer
    host-side (clue match + strict rule check, models/oracle.py) before
    it enters, whatever path produced it (device, fallback, farm, or a
    peer's ``cache_answer`` datagram). A wrong answer is counted and
    dropped; it never becomes cache state. This is the same host-side
    verification contract the PR 5 supervisor applies to device answers
    — here it is unconditional, because a cache write outlives the
    request that produced it.
  * **hit gate** — a hit first proves the requester's board actually IS
    a symmetry of the stored entry by applying the requester's transform
    and comparing grids (never trusting hash equality), then rule-checks
    the de-canonicalized answer against the requester's clues before
    serving. A mismatch (hash collision, tie-resolution divergence, or a
    corrupted entry) reads as a miss — and drops the entry when the
    stored pair itself no longer verifies.

Sharding: the canonical hash picks one of ``shards`` independent
LRU segments, each with its own lock, so concurrent handler threads
(net/fastserve.py's pool) don't serialize on one cache mutex. Capacity
is divided across shards; eviction is per-shard LRU.
"""

from __future__ import annotations

import math
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from .canonical import CanonicalForm, canonicalize


def _solves(board: np.ndarray, solution: np.ndarray) -> bool:
    """Host-side proof that ``solution`` answers ``board``: every clue
    preserved and every row/col/box a permutation of 1..N. The single
    verification predicate both gates use — vectorized (three
    axis-sorts), because it runs on every hit and the hit path's whole
    budget is microseconds. Semantics identical to the test oracle's
    ``oracle_is_valid_solution`` (pinned by tests/test_cache.py)."""
    if (
        board.ndim != 2
        or board.shape[0] != board.shape[1]
        or solution.shape != board.shape
    ):
        return False
    n = board.shape[0]
    b = math.isqrt(n)
    if b * b != n:
        # a Latin-square-shaped payload with a non-perfect-square edge
        # (e.g. a hostile 3×3 cache_answer) passes the row/col checks
        # but has no box structure — reject here, where every gate
        # funnels, instead of letting reshape raise out of the UDP loop
        return False
    clue = board > 0
    if not bool((solution[clue] == board[clue]).all()):
        return False
    want = np.arange(1, n + 1, dtype=solution.dtype)
    if not bool((np.sort(solution, axis=1) == want).all()):
        return False
    if not bool((np.sort(solution, axis=0) == want[:, None]).all()):
        return False
    boxes = solution.reshape(b, b, b, b).transpose(0, 2, 1, 3).reshape(
        n, n
    )
    return bool((np.sort(boxes, axis=1) == want).all())


class _Entry:
    __slots__ = ("board", "solution", "hits", "created")

    def __init__(self, board: np.ndarray, solution: np.ndarray):
        self.board = board
        self.solution = solution
        self.hits = 0
        self.created = time.monotonic()


class AnswerCache:
    """Sharded bounded LRU of verified canonical (board, solution) pairs.

    Args:
      capacity: max entries across all shards (evictions are per-shard
        LRU once a shard's slice fills).
      shards: independent lock domains; the canonical hash picks one.
    """

    def __init__(self, capacity: int = 4096, shards: int = 8):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if shards < 1:
            raise ValueError("shards must be >= 1")
        self.capacity = int(capacity)
        # never more shards than entries (a zero-limit shard would
        # instantly evict everything hashing to it), and distribute the
        # remainder so the shard limits sum to EXACTLY the configured
        # capacity — an operator tuning --answer-cache-capacity must
        # get neither silently more entries (capacity 4 / 8 shards used
        # to hold 8) nor fewer (100/8 used to cap at 96)
        self.shards = max(1, min(int(shards), self.capacity))
        base, extra = divmod(self.capacity, self.shards)
        self._limits = [
            base + (1 if i < extra else 0) for i in range(self.shards)
        ]
        self._maps: List[OrderedDict] = [
            OrderedDict() for _ in range(self.shards)
        ]
        self._locks = [threading.Lock() for _ in range(self.shards)]
        # counters: a benign-race-free single lock — every update is a
        # couple of int ops, far off the shard locks' hot path
        self._stats_lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0
        self.rejected_writes = 0   # failed the write gate (wrong answer)
        self.hit_mismatches = 0    # hash matched, symmetry proof failed
        self.peer_fetches = 0      # cache_get datagrams this node sent
        self.peer_answers = 0      # verified peer answers folded in
        self.peer_rejects = 0      # peer answers that failed verification

    # -- internals ---------------------------------------------------------
    def _shard(self, key: str) -> int:
        return int(key[:8], 16) % self.shards

    def _count(self, field: str, n: int = 1) -> None:
        with self._stats_lock:
            setattr(self, field, getattr(self, field) + n)

    def _put(self, key: str, entry: _Entry) -> None:
        i = self._shard(key)
        evicted = 0
        with self._locks[i]:
            m = self._maps[i]
            if key in m:
                m.move_to_end(key)
                return
            m[key] = entry
            while len(m) > self._limits[i]:
                m.popitem(last=False)
                evicted += 1
        with self._stats_lock:
            self.stores += 1
            self.evictions += evicted

    def _get(self, key: str) -> Optional[_Entry]:
        i = self._shard(key)
        with self._locks[i]:
            m = self._maps[i]
            entry = m.get(key)
            if entry is not None:
                m.move_to_end(key)
                entry.hits += 1
            return entry

    def _peek(self, key: str) -> Optional[_Entry]:
        """Non-mutating read: no hit bump, no LRU touch. The peer-serve
        path uses it — remote ``cache_get`` demand must not pin entries
        against eviction or promote them into the gossiped hot set
        (hot_set ranks by ``hits``; a retry-looping peer would
        otherwise organically inflate a cold key past genuinely
        request-hot ones, sidestepping the advertised-count bounds).
        Peer demand has its own ledger: ``gossip.peer_serves``."""
        i = self._shard(key)
        with self._locks[i]:
            return self._maps[i].get(key)

    def _drop(self, key: str) -> None:
        i = self._shard(key)
        with self._locks[i]:
            self._maps[i].pop(key, None)

    # -- front-door surface ------------------------------------------------
    def lookup(
        self,
        board,
        form: Optional[CanonicalForm] = None,
        count_miss: bool = True,
    ) -> Tuple[Optional[List[List[int]]], Optional[CanonicalForm]]:
        """(solution-in-the-requester's-frame | None, canonical form).

        The returned form is reused by ``store`` on a miss so the
        canonicalization is paid once per request. A hit has been proven
        symmetric (transform application, not hash trust) AND
        rule-checked in the requester's frame before it returns.

        ``count_miss=False`` defers miss accounting to the caller: the
        front door's peer-fetch path probes the store twice for ONE
        request (local miss → fetch → re-probe) and must record exactly
        one hit OR one miss, never both (net/http_api._cache_lookup).
        """
        try:
            form = form or canonicalize(board)
        except (ValueError, TypeError):
            return None, None
        entry = self._get(form.key)
        if entry is None:
            if count_miss:
                self._count("misses")
            return None, form
        # soundness: the recorded transform must actually map the
        # requester's board onto the stored canonical board — equal
        # hashes are evidence, the permutation is the proof
        if not np.array_equal(form.transform.apply(board), entry.board):
            self._count("hit_mismatches")
            if count_miss:
                self._count("misses")
            return None, form
        answer = form.transform.invert(entry.solution)
        if not _solves(np.asarray(board, np.int32), answer):
            # the stored pair no longer verifies in this frame — a
            # corrupted entry must not survive to mislead again
            self._drop(form.key)
            self._count("hit_mismatches")
            if count_miss:
                self._count("misses")
            return None, form
        self._count("hits")
        return answer.tolist(), form

    def _admit(
        self,
        arr: np.ndarray,
        sol: np.ndarray,
        form: Optional[CanonicalForm] = None,
    ) -> bool:
        """THE write pipeline — verify host-side, canonicalize, store —
        shared by every admission path (request answers AND peer
        datagrams), so a future hardening can never apply to one and
        silently skip the other."""
        if not _solves(arr, sol):
            return False
        try:
            form = form or canonicalize(arr)
        except (ValueError, TypeError):
            return False
        self._put(
            form.key,
            _Entry(form.transform.apply(arr), form.transform.apply(sol)),
        )
        return True

    def store(
        self, board, solution, form: Optional[CanonicalForm] = None
    ) -> bool:
        """Admit one answered board. Returns True iff it entered the
        cache — i.e. iff the answer PROVED correct under the write
        gate's host-side verification. Callers never pre-verify; this
        is the single admission point."""
        if solution is None:
            return False
        if not self._admit(
            np.asarray(board, np.int32),
            np.asarray(solution, np.int32),
            form,
        ):
            self._count("rejected_writes")
            return False
        return True

    # -- gossip surface (cache/gossip.py) ----------------------------------
    def get_canonical(self, key: str) -> Optional[Tuple[list, list]]:
        """The stored canonical (board, solution) pair for a peer's
        ``cache_get``, as JSON-ready lists; None when unknown. A PEEK,
        not a hit — see ``_peek``."""
        entry = self._peek(key)
        if entry is None:
            return None
        return entry.board.tolist(), entry.solution.tolist()

    def store_canonical(self, board, solution) -> bool:
        """Fold a peer's ``cache_answer`` payload: the SAME ``_admit``
        pipeline as every other write (a hostile datagram can no more
        poison the cache than a poisoned device program can), keyed by
        OUR OWN canonicalization of the claimed board so the peer
        cannot choose the key it lands under. Only the counters differ:
        the peer ledger, not ``rejected_writes``."""
        try:
            arr = np.asarray(board, np.int32)
            sol = np.asarray(solution, np.int32)
        except (ValueError, TypeError):
            self._count("peer_rejects")
            return False
        if not self._admit(arr, sol):
            self._count("peer_rejects")
            return False
        self._count("peer_answers")
        return True

    def contains(self, key: str) -> bool:
        i = self._shard(key)
        with self._locks[i]:
            return key in self._maps[i]

    def hot_set(self, k: int = 16) -> List[Tuple[str, int]]:
        """Top-``k`` entries by hit count — the gossip digest payload
        (cache/gossip.py). Reads every shard under its own lock; called
        at most once per gossip-digest rebuild, never per request."""
        rows: List[Tuple[str, int]] = []
        for i in range(self.shards):
            with self._locks[i]:
                rows.extend(
                    (key, e.hits) for key, e in self._maps[i].items()
                )
        rows.sort(key=lambda r: (-r[1], r[0]))
        return rows[: max(0, k)]

    # -- operator surface --------------------------------------------------
    def __len__(self) -> int:
        return sum(len(m) for m in self._maps)

    def snapshot(self) -> dict:
        """The ``engine.cost.cache`` block of ``GET /metrics``."""
        with self._stats_lock:
            hits, misses = self.hits, self.misses
            out = {
                "entries": len(self),
                "capacity": self.capacity,
                "shards": self.shards,
                "hits": hits,
                "misses": misses,
                "hit_rate_pct": round(
                    100.0 * hits / (hits + misses), 2
                )
                if hits + misses
                else 0.0,
                "stores": self.stores,
                "evictions": self.evictions,
                "rejected_writes": self.rejected_writes,
                "hit_mismatches": self.hit_mismatches,
                "peer_fetches": self.peer_fetches,
                "peer_answers": self.peer_answers,
                "peer_rejects": self.peer_rejects,
            }
        return out
