"""Cold-start compiler plane: persistent XLA cache wiring + AOT artifacts.

Two layers, both rooted under one operator-chosen directory (CLI
``--compile-cache-dir`` / ``SUDOKU_COMPILE_CACHE_DIR``):

  * ``<dir>/xla`` — jax's own persistent compilation cache, keyed
    implicitly by XLA (HLO fingerprint): any trace-and-compile that
    happened once on this backend is a disk hit next process.
  * ``<dir>/aot`` — our explicit ahead-of-time artifact store
    (``AotStore``): serialized compiled executables keyed by
    (program, board spec, bucket, solver config) + a backend
    fingerprint, loaded with ``jax.experimental.serialize_executable``
    so a warm start skips even the trace. Artifacts are never trusted
    blindly — the engine verifies one round-trip solve against ground
    truth before serving from one, and any load/verify failure falls
    back to ordinary trace-and-compile (never a correctness risk).
"""

from .store import (
    AotStore,
    backend_fingerprint,
    device_fingerprint,
    enable_persistent_cache,
    program_key,
)

__all__ = [
    "AotStore",
    "backend_fingerprint",
    "device_fingerprint",
    "enable_persistent_cache",
    "program_key",
]
