"""On-disk compile artifacts: XLA persistent-cache wiring + AOT executables.

Why this exists (ISSUE 4 / VERDICT r5): a cold ``SolverEngine`` start
compiles its whole bucket ladder from scratch, and inside a short TPU
claim window that compile time IS the session — the round-5 window died
~31 minutes into its first serving-config compile. Everything here turns
a compile paid once into a disk read forever after:

  * ``enable_persistent_cache`` points jax's built-in compilation cache
    at a directory (first-wins: an operator/env-configured dir is never
    overridden, so test suites and the TPU session keep their shared
    caches).
  * ``AotStore`` persists *serialized compiled executables*
    (``jax.experimental.serialize_executable``) under explicit keys, so
    a warm start skips the trace too. A stored artifact is only valid
    for the exact backend that compiled it — ``backend_fingerprint()``
    is stored alongside and checked on load; mismatch (new jax, new
    device kind, different chip count) means "re-compile", never "hope".

Failure policy throughout: any exception on the load path — unreadable
file, truncated pickle, deserialization rejected by the runtime, wrong
fingerprint — returns ``None`` and bumps a counter; the caller falls
back to ordinary trace-and-compile. Corrupt artifacts are deleted so
they cannot fail every future start. The store itself never raises on
the serving path.

Artifacts are pickles (the executable payload plus its pytree specs) in
a cache directory the operator controls — treat the directory like the
XLA cache next to it: machine-local build state, safe to delete any
time, not an interchange format.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

# bump when the artifact layout changes: old artifacts just miss
_FORMAT = 1


def enable_persistent_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``.

    First-wins: when a cache dir is already configured (env
    ``JAX_COMPILATION_CACHE_DIR`` — the test suite and the TPU session
    both set one — or an earlier call), the existing setting is kept and
    this returns False, so an engine flag can never silently re-point a
    session's established cache. Thresholds are zeroed (cache every
    program regardless of compile time/size): the bucket ladder's small
    programs are exactly the ones a cold start pays for.
    """
    import jax

    current = jax.config.jax_compilation_cache_dir
    if current:
        if os.path.abspath(current) != os.path.abspath(cache_dir):
            logger.info(
                "persistent compile cache already at %s — keeping it "
                "(requested %s)", current, cache_dir
            )
        return False
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    logger.info("persistent compile cache at %s", cache_dir)
    return True


def backend_fingerprint() -> str:
    """Identity of the compiling backend: an AOT executable is only
    trusted on the exact (jax version, platform, device kind, device
    count) that produced it. Device *count* matters because the
    executable bakes its device assignment at compile time."""
    import jax

    devs = jax.devices()
    return (
        f"jax={jax.__version__};platform={devs[0].platform};"
        f"kind={devs[0].device_kind};n={len(devs)};format={_FORMAT}"
    )


def device_fingerprint(devices) -> str:
    """Identity of a CONCRETE device assignment (mesh serving, ISSUE 8):
    a serialized executable for a sharded program bakes which physical
    device holds which shard, so the exec tier is only trusted on the
    exact ordered device list that compiled it. The portable StableHLO
    tier deliberately ignores this — any assignment with the same device
    COUNT can recompile it (that is the cross-topology tier a pod node
    cold-starts from)."""
    return ",".join(f"{d.platform}:{d.id}" for d in devices)


def program_key(name: str, spec, bucket: int, config: Dict[str, Any]) -> str:
    """Stable artifact key for one compiled program: the program name,
    board geometry, static batch width, and every solver knob baked into
    the trace (config). Returns a short hex digest used as the artifact
    filename."""
    payload = json.dumps(
        {
            "name": name,
            "size": int(spec.size),
            "box": int(spec.box),
            "bucket": int(bucket),
            "config": {k: config[k] for k in sorted(config)},
        },
        sort_keys=True,
        default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


class AotStore:
    """Explicit ahead-of-time executable store under one directory.

    ``save`` serializes a ``jax`` compiled executable (the object
    returned by ``jit(f).lower(...).compile()``); ``load`` returns a
    callable executable or ``None``. All I/O failures are absorbed into
    counters — callers always have the trace-and-compile fallback.
    """

    def __init__(self, root: str):
        self.root = root
        self.loaded = 0
        self.saved = 0
        self.errors = 0  # failed loads/saves (corrupt, mismatch, io)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.aot")

    def invalidate(self, key: str) -> None:
        """Delete the artifact under ``key`` (verification failure: the
        file deserialized but its executable solved wrong — it must not
        survive to poison the next cold start)."""
        self.errors += 1
        try:
            os.remove(self._path(key))
        except OSError:
            pass

    def stats(self) -> Dict[str, int]:
        return {
            "loaded": self.loaded,
            "saved": self.saved,
            "errors": self.errors,
        }

    def load(self, key: str, fingerprint: str, device_fp: str = None):
        """Load the artifact stored under ``key``.

        Returns ``(callable, kind)`` or ``(None, None)``. Two tiers per
        artifact, tried in order:

          * ``"exec"`` — the serialized compiled executable
            (``jax.experimental.serialize_executable``): zero compile on
            load. PJRT backends differ in support — the CPU runtime in
            this jax generation deserializes to dangling symbol refs —
            so a failure here just falls to the next tier. For sharded
            programs (mesh serving, ISSUE 8) this tier additionally
            requires the stored concrete device assignment
            (``device_fingerprint``) to match ``device_fp`` exactly: a
            serialized executable bakes which device holds which shard.
          * ``"ir"`` — the portable StableHLO module (``jax.export``):
            skips the (expensive) Python re-trace; its compile is a
            persistent-XLA-cache disk hit whenever this backend compiled
            the program before. Cross-topology on purpose: any device
            assignment with the same count can take this tier, so a pod
            node with a different mesh layout still cold-starts off the
            store instead of re-tracing.

        Misses/mismatches return ``(None, None)`` (counted); a file that
        fails BOTH tiers is deleted so it cannot fail every later start.
        """
        path = self._path(key)
        if not os.path.exists(path):
            return None, None
        try:
            with open(path, "rb") as f:
                record = pickle.load(f)
            if record.get("format") != _FORMAT:
                raise ValueError(f"artifact format {record.get('format')!r}")
        except Exception:  # noqa: BLE001 — unreadable/corrupt file
            logger.exception(
                "AOT artifact %s unreadable — deleting, falling back to "
                "compile", key
            )
            self.errors += 1
            try:
                os.remove(path)
            except OSError:
                pass
            return None, None
        if record.get("fingerprint") != fingerprint:
            # not corruption — a different backend compiled this (jax
            # upgrade, CPU-baked artifact on TPU, new topology); leave
            # the file for the backend it belongs to
            logger.info(
                "AOT artifact %s fingerprint mismatch (%s != %s) — "
                "falling back to compile",
                key, record.get("fingerprint"), fingerprint,
            )
            self.errors += 1
            return None, None
        assignment_mismatch = record.get("device_fp") != device_fp
        if assignment_mismatch:
            # a different concrete device assignment compiled this: the
            # exec tier would load a program whose shard placement does
            # not exist here — only the portable IR tier applies
            logger.info(
                "AOT artifact %s: device assignment differs (%s != %s) — "
                "exec tier skipped, trying the StableHLO tier",
                key, record.get("device_fp"), device_fp,
            )
        elif record.get("payload") is not None:
            try:
                from jax.experimental import serialize_executable

                exe = serialize_executable.deserialize_and_load(
                    record["payload"], record["in_tree"], record["out_tree"]
                )
                self.loaded += 1
                return exe, "exec"
            except Exception:  # noqa: BLE001 — backend can't load executables
                logger.info(
                    "AOT artifact %s: executable tier failed to "
                    "deserialize — trying the StableHLO tier", key,
                )
        if record.get("stablehlo") is not None:
            try:
                import jax
                from jax import export as jax_export

                exported = jax_export.deserialize(record["stablehlo"])
                self.loaded += 1
                return jax.jit(exported.call), "ir"
            except Exception:  # noqa: BLE001
                logger.exception(
                    "AOT artifact %s: StableHLO tier failed too — %s",
                    key,
                    "keeping (assignment mismatch: the exec tier may "
                    "still serve its own topology)"
                    if assignment_mismatch
                    else "deleting",
                )
        self.errors += 1
        if assignment_mismatch:
            # not corruption — the exec tier belongs to another topology
            # and this file may still serve it; keep the artifact
            return None, None
        try:
            os.remove(path)
        except OSError:
            pass
        return None, None

    def save(
        self,
        key: str,
        compiled: Any,
        fingerprint: str,
        meta: Optional[Dict[str, Any]] = None,
        stablehlo: Optional[bytes] = None,
        device_fp: Optional[str] = None,
    ) -> bool:
        """Serialize ``compiled`` (and optionally its portable StableHLO
        twin from ``jax.export``) under ``key``. Atomic (tmp + rename, so
        a crashed writer can't leave a half-artifact that poisons every
        later cold start). ``device_fp`` records the concrete device
        assignment a sharded program was compiled against (the exec tier's
        extra gate; None for single-device programs — back-compatible with
        every pre-mesh artifact). Best-effort: False on failure, never
        raises."""
        try:
            payload = in_tree = out_tree = None
            try:
                from jax.experimental import serialize_executable

                payload, in_tree, out_tree = serialize_executable.serialize(
                    compiled
                )
            except Exception:  # noqa: BLE001 — executable tier optional
                logger.info(
                    "AOT artifact %s: executable serialization "
                    "unsupported here — saving the StableHLO tier only",
                    key,
                )
            if payload is None and stablehlo is None:
                self.errors += 1
                return False
            record = {
                "format": _FORMAT,
                "fingerprint": fingerprint,
                "device_fp": device_fp,
                "meta": meta or {},
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "stablehlo": stablehlo,
            }
            os.makedirs(self.root, exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=self.root, prefix=f".{key}.", suffix=".tmp"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump(record, f)
                os.replace(tmp, self._path(key))
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self.saved += 1
            return True
        except Exception:  # noqa: BLE001 — saving is an optimization only
            logger.exception("AOT artifact %s save failed", key)
            self.errors += 1
            return False
