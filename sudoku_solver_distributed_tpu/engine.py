"""Serving engine: warmed, bucketed, optionally mesh-sharded batch solving.

The reference's solving entry point is one HTTP thread calling a Python loop
per cell (reference node.py:534-557). Here the entry point is a *pre-compiled*
device program: request boards are padded into a small set of static batch
buckets (so no request ever pays a trace/compile), solved in one device call,
and the per-board validation-sweep counts are folded into host-side stats.

p50-latency contract (BASELINE.md north star <5 ms): ``warmup()`` compiles
every bucket ahead of serving, so a single-puzzle ``/solve`` is one
donated-buffer device call on a hot program.

Cold-start contract (ISSUE 4): warmup is *tiered* — the smallest serving
bucket (and the coalescer's preferred bucket) compiles first, so ``/solve``
is servable after tier 0 while the rest of the ladder widens (optionally in
a background thread, optionally under a ``budget_s`` so a short TPU claim
window spends its seconds on the buckets the bench will hit). The
deep/quick program variants share ONE compiled executable per bucket (the
iteration budget is a traced argument, not a baked constant), and with a
``compile_cache_dir`` both jax's persistent XLA cache and an explicit AOT
artifact store (compilecache/) turn every compile paid once into a disk
read forever after. ``warmed`` now means "tier-0 warm" (servable);
``fully_warmed`` is the old every-bucket signal.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .obs.cost import CostAccounting
from .obs.trace import current_trace
from .ops import BoardSpec, SPEC_9, solve_batch
from .ops import solver as _solver
from .ops.config import SERVING_CONFIG
from .ops.solver import OVERFLOW, RUNNING, SOLVED
from .utils.profiling import annotate, device_trace

logger = logging.getLogger(__name__)


DEFAULT_BUCKETS = (1, 8, 64, 512, 4096)

# constructor sentinel: "use ops.SERVING_CONFIG for this board size" —
# distinct from an explicit None, which means the kernel's own default
_AUTO = object()


class _SegmentHandle:
    """One in-flight continuous-batching segment (PR 15): everything
    ``finalize_segment`` needs to fetch, account, and close the
    supervision token, plus the carried-forward pool ``state`` — which
    is available at DISPATCH time, so the driver can chain segment N+1
    off it before segment N's digest is ever read (the one-deep pipeline
    at the segment seam). On the pipelined arm ``digest``/``gathered``
    are the split device outputs (two-phase fetch) and ``rows`` is None;
    on the PR 12 arm ``rows`` is the full packed device array."""

    __slots__ = (
        "state", "digest", "gathered", "rows", "token", "t0", "width",
        "injected", "pipelined", "boundary_host_s",
    )

    def __init__(
        self, *, state, digest, gathered, rows, token, t0, width,
        injected, pipelined, boundary_host_s,
    ):
        self.state = state
        self.digest = digest
        self.gathered = gathered
        self.rows = rows
        self.token = token
        self.t0 = t0
        self.width = width
        self.injected = injected
        self.pipelined = pipelined
        self.boundary_host_s = boundary_host_s


class SolverEngine:
    """Batched sudoku solving behind static-shape compiled programs.

    Args:
      spec: board geometry (default classic 9×9).
      buckets: ascending static batch sizes; a request of B boards runs in
        the smallest bucket ≥ B (or tiles over the largest).
      max_depth: guess-stack capacity override passed to the kernel.
        Unspecified → the measured staged depth from ops.SERVING_CONFIG
        (shallow fast path + full-depth retry); explicit None → the flat
        per-spec safe default.
      mesh: the mesh-parallel serving plane (ISSUE 8). "auto" — the CLI
        serving default — builds a 1-D ``data`` mesh over every local
        device when more than ``ops.config.MESH_SERVING["auto_min_devices"]
        - 1`` are present (and an explicit ``sharding=`` was not given —
        a pinned placement wins over auto), and every bucket program becomes a
        shard_map-over-``data`` collective (parallel/shard.
        make_packed_serving_program): one coalesced batch is split across
        all chips instead of leaving N−1 idle. Pass an explicit
        ``jax.sharding.Mesh`` (1-D, axis ``"data"``) to pin the device
        set, or None (library default) for the single-device programs.
        Bucket widths round UP to mesh-divisible multiples (recorded in
        ``mesh_info()``); results stay bit-identical to single-device —
        the per-board search trajectory is schedule-independent
        (tests/test_mesh_serving.py parity). xla backend only.
      bucket_multiple: round bucket widths up to multiples of this instead
        of the mesh size (multi-host serving: the CLI passes the GLOBAL
        device count so leader fan-out batches divide the pod-wide mesh
        while each host's own programs run on its local mesh).
      sharding: optional jax.sharding.Sharding for the batch axis — supply a
        NamedSharding over a device mesh to fan one bucket out across chips
        (the TPU-native analog of the reference's peer task farm). The
        ``mesh=`` plane supersedes this (and sets it internally); the raw
        parameter remains for placement-only use without sharded programs.
      frontier_mesh: optional jax.sharding.Mesh — when set, single-board
        ``solve_one`` requests are routed through the sharded search-frontier
        race (parallel/frontier.py): the board's DFS subtrees are raced
        across the mesh with a per-iteration early-exit psum. This makes the
        multi-chip latency path the serving path for ``POST /solve``, the
        way the reference's distributed dispatch is its serving path
        (reference node.py:427-475).
      frontier_states_per_device: speculative states seeded per chip for the
        frontier race.
      backend: batch kernel implementation — "xla" (ops/solver.py, the
        compacted lockstep loop; default) or "pallas" (ops/pallas_solver.py,
        the VMEM-resident per-block kernel; interpret mode is selected
        automatically off-TPU so tests run anywhere).
      locked_candidates: locked-set eliminations — locked candidates
        (pointing + claiming) and optionally naked pairs — in the solver's
        analysis sweeps: sound, ~1.7× faster on hard corpora (ops/solver.py).
        Default: ops.SERVING_CONFIG for the xla backend; unsupported by the
        pallas kernel (passing True with it raises).
      naked_pairs: pair detection inside locked sweeps (None →
        ops.SERVING_CONFIG; see ops/config.py for the measured rationale).
      max_iters: lockstep iteration budget per device call (None →
        ops.SERVING_CONFIG).
      coalesce: route bucket-path ``solve_one``/``solve_one_async`` calls
        through the request-coalescing micro-batch scheduler
        (parallel/coalescer.py) so concurrent requests share one device
        call. Default on — this is the serving path; False restores the
        seed's one-device-call-per-request behavior.
      coalesce_max_wait_s: longest a lone request waits for co-riders
        before its batch dispatches anyway (default 2 ms — the <5 ms p50
        contract minus headroom).
      coalesce_quiescence_s / coalesce_burst_wait_s: burst-absorption
        tuning (parallel/coalescer.py): at the max-wait deadline the
        dispatcher keeps absorbing while requests arrived within the
        last quiescence_s (default 1 ms), bounded by burst_wait_s past
        the oldest arrival (default 10× max-wait). A lone request is
        never delayed by either.
      coalesce_inflight_depth: dispatched-but-unfetched batches the
        coalescer pipelines (2 = host/device double buffering).
      coalesce_max_batch: cap on boards per coalesced device call (None →
        the largest bucket). On TPU the widest bucket is the whole point;
        on the CPU fallback a wide batch of MIXED boards pays the
        worst board's iteration count across the full width (lockstep
        batch semantics) and per-board throughput collapses past the
        SIMD sweet spot — measured hard-corpus boards/s on 2 cores:
        batch-1 552, batch-8 2758, batch-64 854. Serving benches cap at
        8 on CPU (bench.py --mode concurrent).
      coalesce_adaptive: scale the three coalescer wait budgets with the
        measured arrival rate (serving/load.AdaptiveWaitPolicy): the
        configured values become CAPS — near-zero wait when idle (a lone
        request dispatches immediately, strictly better latency than the
        fixed budget), the full budgets under load (full buckets). Off by
        default: fixed budgets, exactly the PR 1 behavior.
      continuous: continuous batching (PR 12) — the coalesced serving
        path runs the device loop OPEN-LOOP: bounded ``segment_iters``
        -iteration segments over a fixed-width lane pool, finished lanes
        resolved (futures answered) between segments and freshly admitted
        boards injected into the freed slots on-device
        (ops/solver.run_segment, parallel/coalescer.py). None (default)
        resolves from ops.config.CONTINUOUS_SERVING — ON for the
        coalesced xla bucket path; ``continuous=False`` (CLI
        ``--no-continuous``) restores the closed-loop run-to-completion
        dispatcher, the A/B arm of ``bench.py --mode continuous``.
        Answers are bit-identical either way (segmenting is
        schedule-independent, tests/test_continuous.py). Requires
        ``coalesce=True`` and the xla backend; engines with a raw
        ``sharding=`` but no mesh plane keep the closed loop.
      segment_iters: lockstep iterations per continuous-batching segment
        (the sweepable k — None resolves ops.config.SEGMENT per board
        size). Smaller = finished lanes refill sooner (higher sustained
        lane utilization, lower deadline-conditioned tails), larger
        amortizes segment dispatch overhead.
      segment_pipeline: the pipelined segment boundary (PR 15, continuous
        path only — None resolves ops.config.SEGMENT_PIPELINE, ON). The
        segment program DONATES its state buffers (the carried
        (width, D, C) stack updates in place instead of copying every
        segment) and returns a compact per-lane completion digest next to
        the device-resident state; the host fetches the digest every
        boundary and full solution rows only for newly-solved lanes
        (two-phase fetch — ~80× fewer boundary bytes at 25×25), and the
        coalescer's driver overlaps boundary host work with device
        compute (parallel/coalescer.py). ``segment_pipeline=False`` (CLI
        ``--no-segment-pipeline``) restores the PR 12 boundary
        byte-for-byte — full-row fetch, no donation, strictly serial
        boundaries — the A/B arm of ``bench.py --mode continuous``.
        Answers are bit-identical either way (the digest/gather split
        never touches board trajectories; tests/test_continuous.py).
      compile_cache_dir: root of the persistent compile plane
        (compilecache/): ``<dir>/xla`` hosts jax's persistent compilation
        cache (first-wins — an env/session-configured cache dir is never
        re-pointed), ``<dir>/aot`` the explicit AOT artifact store: warmup
        loads serialized executables keyed by (program, spec, bucket,
        solver config, backend fingerprint) and verifies one round-trip
        solve before trusting each; any mismatch/corruption falls back to
        trace-and-compile. None (default): no persistent plane, exactly
        the prior behavior.
      aot_artifacts: with ``compile_cache_dir``, also use the explicit
        AOT store (default True). False keeps only the implicit XLA
        cache — the coldstart bench A/Bs the two layers separately.
      solver_config: hot-loop escape hatch (PR 7): a preset name
        ("default" | "legacy") or a dict of raw ``solve_batch`` overrides
        (packed / compact_div / compact_floor / compact_every /
        legacy_loop — ops/config.resolve_solver_overrides). "legacy"
        restores the pre-PR7 loop for A/B (``bench.py --mode hotloop``)
        on every solve path — bucket programs, the quick-state probe,
        the frontier race's step loop, and the sharded solver; only the
        one-off seeding/finalize helper sweeps keep the default analysis
        (bit-identical outputs either way). xla backend only.

    All unspecified solver knobs resolve from ops.SERVING_CONFIG, the single
    definition site shared with bench.py and __graft_entry__ — the benched
    configuration is provably the served one (VERDICT r2 weak #1).
    """

    def __init__(
        self,
        spec: BoardSpec = SPEC_9,
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_depth=_AUTO,
        mesh=None,
        bucket_multiple: Optional[int] = None,
        sharding: Optional[jax.sharding.Sharding] = None,
        frontier_mesh: Optional[jax.sharding.Mesh] = None,
        frontier_states_per_device: int = 64,
        frontier_route: str = "auto",
        frontier_escalate_iters: int = 512,
        frontier_handoff: bool = False,
        backend: str = "xla",
        locked_candidates: Optional[bool] = None,
        waves: Optional[int] = None,
        naked_pairs: Optional[bool] = None,
        max_iters: Optional[int] = None,
        deep_retry_factor: int = 16,
        coalesce: bool = True,
        coalesce_max_wait_s: float = 0.002,
        coalesce_quiescence_s: float = 0.001,
        coalesce_burst_wait_s: Optional[float] = None,
        coalesce_inflight_depth: int = 2,
        coalesce_max_batch: Optional[int] = None,
        coalesce_adaptive: bool = False,
        continuous: Optional[bool] = None,
        segment_iters: Optional[int] = None,
        segment_pipeline: Optional[bool] = None,
        deep_lane_cap: int = 0,
        compile_cache_dir: Optional[str] = None,
        aot_artifacts: bool = True,
        solver_config=None,
    ):
        if backend not in ("xla", "pallas"):
            raise ValueError(f"unknown engine backend {backend!r}")
        if backend == "pallas" and (sharding is not None or mesh is not None):
            # pallas_call has no GSPMD partitioning rule: the sharded bucket
            # would either fail to compile or silently replicate onto every
            # chip. Mesh fan-out for the pallas kernel needs a shard_map
            # wrapper (ROADMAP); refuse rather than mislead.
            raise ValueError(
                "backend='pallas' does not compose with mesh=/sharding= — "
                "use the xla backend for mesh-sharded buckets"
            )
        self.spec = spec
        # Mesh-parallel serving plane (ISSUE 8): resolve the batch mesh
        # before the bucket ladder — widths round to mesh-divisible
        # multiples so every coalesced batch splits over all devices.
        if mesh == "auto":
            from .ops.config import mesh_serving_config

            # LOCAL devices only: jax.devices() spans every host once
            # jax.distributed is initialized, and a pod-global program
            # dispatched by one host outside the lockstep serving loop
            # would hang on followers that never enter the collective
            # (multi-host fan-out goes through engine.mesh_runner, wired
            # explicitly by the CLI). An explicit sharding= wins over
            # auto — the caller pinned a placement; keep the raw
            # sharding contract instead of silently overwriting it.
            local = jax.local_devices()
            mesh = None
            if (
                sharding is None
                and len(local) >= mesh_serving_config()["auto_min_devices"]
            ):
                from .parallel.mesh import default_mesh

                mesh = default_mesh(local)
        elif mesh is not None:
            if sharding is not None:
                raise ValueError(
                    "mesh= and sharding= are mutually exclusive — the mesh "
                    "plane derives its own data-axis sharding"
                )
            if "data" not in getattr(mesh, "axis_names", ()):
                raise ValueError(
                    "mesh= must be a 1-D jax.sharding.Mesh with a 'data' "
                    f"axis, got axes {getattr(mesh, 'axis_names', None)!r}"
                )
        self.mesh = mesh
        if mesh is not None:
            from .parallel.mesh import data_sharding

            sharding = data_sharding(mesh)
        # Leader fan-out hook (multi-host mesh serving): when set (a
        # callable (padded_boards, iters) -> host packed rows), bucket
        # dispatches route through the SPMD serving loop so every pod
        # host's devices enter the collective (parallel/serving_loop.py
        # solve_padded); the CLI wires it on the leader. None: local
        # dispatch through this engine's own programs.
        self.mesh_runner = None
        buckets = tuple(sorted(set(buckets)))
        self.requested_buckets = buckets
        if mesh is not None or bucket_multiple:
            from .ops.config import mesh_serving_config

            fill = mesh_serving_config()["min_per_device_fill"]
            mult = int(
                bucket_multiple
                or (mesh.devices.size * fill if mesh is not None else 1)
            )
            buckets = tuple(sorted({-(-b // mult) * mult for b in buckets}))
            if buckets != self.requested_buckets:
                logger.info(
                    "mesh serving: bucket ladder %s rounded to "
                    "mesh-divisible %s (multiple %d)",
                    self.requested_buckets, buckets, mult,
                )
        self.buckets = buckets
        # mesh dispatch counters (under _lock): the batch-split evidence
        # mesh_info()/ /metrics report — how many sharded dispatches ran
        # and how the LAST batch actually landed on the mesh (read from
        # the output array's sharding metadata, parallel/shard.
        # split_evidence)
        self.mesh_dispatches = 0
        self.mesh_runner_dispatches = 0
        self._mesh_last_split: dict = {}
        self._mesh_min_devices: Optional[int] = None
        # Unspecified knobs resolve from ops.SERVING_CONFIG — ONE definition
        # site shared with bench.py and __graft_entry__ (VERDICT r2 weak #1),
        # so the benched configuration IS the served one. Custom board sizes
        # outside the config fall back to the kernel defaults.
        cfg = SERVING_CONFIG.get(spec.size, {})
        if max_depth is _AUTO:
            max_depth = cfg.get("max_depth")
        self.max_depth = max_depth
        self.sharding = sharding
        self.frontier_mesh = frontier_mesh
        self.frontier_states_per_device = frontier_states_per_device
        if frontier_route not in ("auto", "always"):
            raise ValueError(
                f"frontier_route must be 'auto' or 'always', got "
                f"{frontier_route!r}"
            )
        # Per-request routing between the two single-board serving paths
        # (VERDICT r3 task 3). "always": every auto solve_one rides the
        # race — the pre-r3 global-flag behavior. "auto": a bucket-path
        # probe at ``frontier_escalate_iters`` answers the easy mass, and
        # only boards still RUNNING at that budget — the deep-search tail
        # the race exists for — escalate to the frontier. Measured, round 4
        # (benchmarks/exp_frontier_crossover.py over the three-run union
        # corpus — two seeds x two mining methods, merge_deep.py;
        # benchmarks/xo_union_r4.json, 288 boards): the measured crossover
        # is 498 lockstep iterations — the race wins 229/250 boards at or
        # above the 512 default (92%) and only 5/38 below it, 0/32 on
        # ordinary hard boards, and on beyond-cap boards (all 87 with
        # iters>=4096) it is ~6.8x faster (45.6 vs 312.2 ms p50) even with
        # ONE device's 64 speculative states — the single-chip case. Round 3's
        # single-run corpus put the crossover at 3039 with nothing mined in
        # the 110-3039 gap; the union fills that gap and lands the boundary
        # just under the default, so 512 stands validated rather than
        # one-seed-lucky (VERDICT r3 task 5). The race must beat the bucket
        # path somewhere to be more than decoration (the reference's
        # distributed path vs its local one, reference node.py:427-475);
        # auto routing sends it exactly that somewhere. The default is also
        # safe at the other shipped sizes: per-board probe-view sweep
        # maxima on the committed corpora are 414 (16x16, p99=122) and 93
        # (25x25) — benchmarks/exp_probe_sweeps.py, probe_sweeps_r4.json —
        # so no ordinary board spuriously escalates at 512. And it pays off
        # at 16x16 too: over an annealing-mined deep-hexadoku corpus
        # (xo_16_r4.json, 80 boards) the race wins 58/64 deep boards and
        # 0/16 ordinary ones, reaching ~37x over the bucket path on the
        # deepest decile (19 vs 718 ms p50) — the mined corpus starts at
        # 1712 iters, so 512 sits safely inside the [414, 1712] dead zone
        # between the deepest ordinary board and the shallowest deep one.
        # 25x25 (xo_25_r4.json, mined deep corpus): race wins 8/8 boards
        # at-or-above 512 at ~2.5x (31-32 vs 74-81 ms p50) and mostly
        # loses below it — the default holds at all three shipped sizes.
        self.frontier_route = frontier_route
        self.frontier_escalate_iters = frontier_escalate_iters
        # Probe→race state handoff (VERDICT r3 task 6): escalated requests
        # seed the race from the probe's unexplored subtrees instead of the
        # root, so the probe's iterations are not re-paid. MEASURED AND
        # REJECTED as the default (benchmarks/exp_handoff.py,
        # handoff_cpu_r4.json 2026-07-30: deep-corpus p50 86.0 ms handoff
        # vs 73.4 ms root-restart, root wins 47/48, verdicts agree, oracle
        # OK): the probe descends a single MRV chain, so its refuted
        # region — the only thing a handoff saves — is tiny, while the
        # stack-chain decomposition it hands over is far less balanced
        # than a fresh MRV BFS split from the root. Off by default; kept
        # as an opt-in (CLI --frontier-handoff) because the trade could
        # flip where seeding RTTs dominate. Local-mesh race only either
        # way: the multi-host serving loop's broadcast carries a bare
        # board, and the followers never saw the leader's probe state.
        self.frontier_handoff = frontier_handoff
        self.backend = backend
        if locked_candidates is None:
            locked_candidates = (
                cfg.get("locked_candidates", True) if backend == "xla" else False
            )
        if locked_candidates and backend == "pallas":
            raise ValueError(
                "locked_candidates is not supported by the pallas kernel"
            )
        self.locked_candidates = locked_candidates
        # propagation sweeps fused per lockstep iteration (ops/solver.py);
        # per-size measured winners live in ops.SERVING_CONFIG (9×9: 3 —
        # v5e 2026-07-30: waves=2 258k → waves=3 277k puzzles/s/chip;
        # 16×16/25×25: 1). Pallas has no wave support.
        if waves is None:
            waves = cfg.get("waves", 1) if backend == "xla" else 1
        if waves != 1 and backend == "pallas":
            raise ValueError(
                "waves is not supported by the pallas kernel"
            )
        self.waves = waves
        # naked-pair detection inside locked sweeps (None → config; see
        # ops/config.py for the measured rationale)
        if naked_pairs and backend == "pallas":
            # same contract as locked_candidates/waves: the pallas kernel
            # has no pair support — refuse rather than silently ignore
            raise ValueError(
                "naked_pairs is not supported by the pallas kernel"
            )
        if naked_pairs is None:
            naked_pairs = (
                cfg.get("naked_pairs", locked_candidates)
                if backend == "xla"
                else False
            )
        self.naked_pairs = naked_pairs
        # Hot-loop overrides (the --solver-config escape hatch): resolved
        # once here, applied to every bucket program's solve_batch call and
        # surfaced at warm_info()["solver_loop"] so a serving node's active
        # compaction schedule is observable from /metrics.
        from .ops.config import resolve_solver_overrides

        self.solver_overrides = resolve_solver_overrides(solver_config)
        if self.solver_overrides and backend == "pallas":
            raise ValueError(
                "solver_config overrides apply to the xla hot loop only — "
                "the pallas kernel has its own block-granular schedule"
            )
        if max_iters is None:
            max_iters = cfg.get("max_iters", 4096)
        # Iteration budget per device call, and the RUNNING safety net: a
        # board still RUNNING at the cap (possible only for adversarial
        # inputs — the whole 2000-board fuzz corpus finishes within 4096
        # under the serving config, tests/test_fuzz_solver.py) is re-solved
        # once at ``deep_retry_factor ×`` the budget rather than misreported
        # as "no solution" (the reference would grind forever instead,
        # reference node.py:427-475). A board capped even by the retry is
        # surfaced as ``info["capped"]`` by solve_batch_np. Both values are
        # baked into the compiled closures below — constructor-only, frozen
        # after init (unlike waves/locked_candidates they are never re-read).
        self.max_iters = max_iters
        self.deep_retry_factor = deep_retry_factor
        # Multi-host frontier serving: when set (a callable board ->
        # (solution | None, info)), single-board solves delegate to it
        # instead of calling frontier_solve locally — the CLI points this
        # at FrontierServingLoop.solve on the leader host so every host
        # enters the collective race in lockstep (parallel/serving_loop.py).
        # frontier_loop is the loop object itself (for health reporting) —
        # set it alongside frontier_runner when the runner wraps a loop.
        self.frontier_runner = None
        self.frontier_loop = None
        # when set, batch device calls are captured as jax.profiler traces
        # under this directory (utils/profiling.py; CLI --profile-dir); only
        # one trace can be active per process, so concurrent requests skip
        # tracing instead of crashing (_profile_mutex)
        self.profile_dir: Optional[str] = None
        self._profile_mutex = threading.Lock()
        # jax.profiler hook (ISSUE 6 satellite, CLI --device-trace-dir):
        # when armed, ONE warmup pass and the first N supervised device
        # calls each leave an XLA trace artifact under this directory —
        # a TPU window run produces profiler evidence with no code edits.
        # Counters ride warm_info() so the capture is observable from
        # /metrics. Mutations under _warm_lock; the capture itself shares
        # _profile_mutex with --profile-dir (one active trace per process).
        self.device_trace_dir: Optional[str] = None
        self._device_trace_budget = 0
        self._device_trace_captured = 0
        self._warmup_trace_done = False
        self._lock = threading.Lock()
        # cumulative engine effort, the analog of the reference's
        # `validations` counter (node.py:87): one unit per analysis sweep per
        # active board.
        self.validations = 0
        self.solved_puzzles = 0
        # /solve requests answered by the bucket path because the frontier
        # path raised (loop death, failed collective) — health signal,
        # exposed at /metrics (net/http_api.py)
        self.frontier_fallbacks = 0
        # auto-routed requests whose quick probe hit the escalation budget
        # and went to the race (frontier_route="auto")
        self.frontier_escalations = 0
        # Request coalescing (parallel/coalescer.py): single-board solves on
        # the bucket path ride a shared micro-batch scheduler so concurrent
        # clients fill the pre-compiled buckets instead of each paying a
        # batch-1 device call. Lazily constructed (threads only exist once
        # the serving path is actually exercised); frontier-routed requests
        # bypass it (solve_one).
        self.coalesce = coalesce
        self.coalesce_max_wait_s = coalesce_max_wait_s
        self.coalesce_quiescence_s = coalesce_quiescence_s
        self.coalesce_burst_wait_s = coalesce_burst_wait_s
        self.coalesce_inflight_depth = coalesce_inflight_depth
        self.coalesce_max_batch = coalesce_max_batch
        self.coalesce_adaptive = coalesce_adaptive
        # Continuous batching (ISSUE 12): the open-loop segmented serving
        # device loop — resolved here, program built below, driven by the
        # coalescer's segment loop (parallel/coalescer.py).
        from .ops.config import CONTINUOUS_SERVING, resolved_segment_shape

        self.segment_shape = resolved_segment_shape(spec.size, segment_iters)
        self.segment_iters = self.segment_shape["k"]
        if continuous is None:
            continuous = (
                CONTINUOUS_SERVING["default_on"]
                and coalesce
                and backend == "xla"
                # a raw sharding= without the mesh plane has no sharded
                # segment program to dispatch through — keep closed-loop
                and (sharding is None or self.mesh is not None)
            )
        elif continuous:
            if backend != "xla":
                raise ValueError(
                    "continuous batching needs the xla backend — the "
                    "pallas kernel bakes a static iteration bound and "
                    "cannot carry resumable segment state"
                )
            if not coalesce:
                raise ValueError(
                    "continuous batching rides the coalesced serving "
                    "path — it cannot be enabled with coalesce=False"
                )
            if sharding is not None and self.mesh is None:
                # same reason the default resolution skips this shape: a
                # raw placement has no sharded segment program, and the
                # resumable pool state would silently ignore the caller's
                # sharding — refuse rather than mislead
                raise ValueError(
                    "continuous batching with a raw sharding= needs the "
                    "mesh plane (mesh=) — the lane-pool state has no "
                    "sharded segment program to ride otherwise"
                )
        self.continuous = bool(continuous)
        # Pipelined segment boundary (PR 15): donation + digest-only
        # fetch + overlapped host refill on the continuous path. Resolved
        # here so the program build below and _program_config() agree by
        # construction; False restores the PR 12 boundary byte-for-byte.
        from .ops.config import SEGMENT_PIPELINE

        if segment_pipeline is None:
            segment_pipeline = (
                SEGMENT_PIPELINE["default_on"] and self.continuous
            )
        elif segment_pipeline and not self.continuous:
            raise ValueError(
                "segment_pipeline=True needs continuous batching — the "
                "pipelined boundary is the continuous path's segment "
                "seam (closed-loop dispatch already pipelines via "
                "inflight_depth)"
            )
        self.segment_pipeline = bool(segment_pipeline)
        # long-job lane cap for the continuous driver (ISSUE 13
        # satellite, CLI --deep-lane-cap): bound the lanes deep-resident
        # boards may hold while fresh demand queues; overage evicts to
        # the deep-retry net (parallel/coalescer.py). 0 = off.
        self.deep_lane_cap = int(deep_lane_cap)
        self._coalescer = None
        self._coalescer_init_lock = threading.Lock()
        # Failure-domain supervision (ISSUE 5, serving/health.py): when an
        # EngineSupervisor is attached it opens a watchdog token around
        # every bucket-path device call (_dispatch_padded/_finalize_padded),
        # bucket selection routes around quarantined widths, and the
        # single-board serving path reroutes through the host-oracle
        # fallback while the breaker is open. None (default): zero cost,
        # byte-identical behavior.
        self.supervisor = None
        # engine-seam chaos hook (utils/faults.EngineFaultInjector): when
        # set, every bucket dispatch/fetch passes through it — fail-next-N,
        # injected latency (watchdog food), bucket poisoning. None costs
        # nothing; counters surface under /metrics "faults".
        self.fault_injector = None
        # Device cost accounting (ISSUE 10, obs/cost.py): every finalized
        # bucket dispatch records wall time, batch fill, pad waste
        # (coalescer vs mesh-rounding, split), and the PR 7 LoopStats
        # lane/idle counters threaded out of the compiled program as two
        # trailing packed-row columns. One locked append per BATCH —
        # never per request — surfaced at /metrics engine.cost.
        self.cost = CostAccounting()
        # Warm-state plane (ISSUE 4). `warmed` flips at TIER-0 warm — the
        # smallest serving bucket (+ the coalescer's preferred bucket and
        # the probe program) compiled, i.e. /solve is servable without
        # paying a compile; `fully_warmed` is the old every-bucket (and
        # frontier-rung) signal benches gate on. Per-bucket detail in
        # warm_info(), surfaced at /metrics under engine.warm.
        self.warmed = False
        self.fully_warmed = False
        self._warm_lock = threading.Lock()
        self._warm_state: dict = {}   # bucket -> {warm, source, compile_s}
        self._warm_order: list = []   # buckets in the order warmup compiled
        self._warm_skipped: list = []  # buckets a warmup budget cut off
        self._warmup_started = False
        self._warm_thread: Optional[threading.Thread] = None
        # distinct device programs dispatched, keyed (name, batch width) —
        # the compile-cost counter tests assert on: the deep/quick/normal
        # variants share one program per bucket (max_iters is traced), so
        # a fully-warm xla engine holds exactly len(buckets) programs
        # (+1 for the handoff probe), not 3× that.
        self._programs: set = set()
        # Persistent compile plane (compilecache/): implicit XLA disk
        # cache + explicit AOT executable store. AOT executables install
        # into _aot_execs[bucket] and take priority over the jit path.
        # Mesh engines use the store too (the PR 4 gap, closed in ISSUE
        # 8): the serialized-executable tier is additionally keyed by the
        # concrete device assignment (compilecache.device_fingerprint)
        # and the portable StableHLO tier is the cross-topology fallback;
        # only a RAW sharding= without the mesh plane still skips it (no
        # mesh to derive sharded avals from).
        self.compile_cache_dir = compile_cache_dir
        self._aot_store = None
        self._aot_execs: dict = {}
        self._iter_scalars: dict = {}  # iteration budget -> device scalar
        if compile_cache_dir:
            from .compilecache import AotStore, enable_persistent_cache

            enable_persistent_cache(os.path.join(compile_cache_dir, "xla"))
            if aot_artifacts and backend == "xla" and (
                sharding is None or mesh is not None
            ):
                self._aot_store = AotStore(
                    os.path.join(compile_cache_dir, "aot")
                )

        def _run(grid, mi=max_iters):
            B = grid.shape[0]
            # Fused waves amortize the step's merge/stack machinery over a
            # batch; a single board has nothing to amortize — extra sweeps
            # only add latency to the request path (measured on the README
            # board, 1 CPU core: waves=1 p50 1.17 ms vs waves=3 1.55 ms).
            # B is static at trace time, so each bucket compiles its own
            # choice: 1-board buckets sweep once, batches use self.waves.
            waves_eff = 1 if B == 1 else self.waves
            if self.backend == "pallas":
                from .ops.pallas_solver import solve_batch_pallas

                # block is a lane width: always 128 on TPU (Mosaic tiling —
                # the kernel pads small buckets up to a block multiple);
                # interpret mode matches so both paths run the same shapes
                res, lstats = solve_batch_pallas(
                    grid,
                    self.spec,
                    block=128,
                    max_depth=self.max_depth,
                    max_iters=mi,
                    interpret=jax.default_backend() != "tpu",
                    return_stats=True,
                )
            else:
                res, lstats = solve_batch(
                    grid,
                    self.spec,
                    max_depth=self.max_depth,
                    max_iters=mi,
                    locked_candidates=self.locked_candidates,
                    waves=waves_eff,
                    naked_pairs=self.naked_pairs,
                    return_stats=True,
                    **self.solver_overrides,
                )
            # Pack every result field into ONE int32 array: the serving path
            # pays exactly one device→host transfer per request. (Unpacked,
            # each field is its own transfer — at ~70 ms RTT over a tunneled
            # TPU that quadruples request latency.) The two trailing
            # columns carry the call's LoopStats scalars broadcast across
            # rows (lane_steps / idle_lane_steps — obs/cost.py reads row 0)
            # so the loop-work counters ride the SAME single transfer.
            return jnp.concatenate(
                [
                    res.grid.reshape(B, -1),
                    res.solved[:, None].astype(jnp.int32),
                    res.status[:, None],
                    res.guesses[:, None],
                    res.validations[:, None],
                    jnp.broadcast_to(lstats.lane_steps, (B,))[:, None],
                    jnp.broadcast_to(lstats.idle_lane_steps, (B,))[:, None],
                ],
                axis=1,
            )

        # no donate_argnums: the packed output can never alias the input
        # buffer (different trailing shape), so donation would be a no-op
        # that only emits "donated buffers were not usable" warnings
        if backend == "pallas":
            # The Mosaic kernel shapes its loop from a STATIC iteration
            # bound, so the pallas path keeps one jit per variant: the
            # deep safety net and the auto-route probe compile lazily on
            # first use, counted per (variant, width).
            self._program = None
            self._solve = self._counted("solve", jax.jit(_run))
            self._solve_deep = self._counted(
                "deep",
                jax.jit(lambda grid: _run(grid, max_iters * deep_retry_factor)),
            )
            self._solve_quick = self._counted(
                "quick",
                jax.jit(lambda grid: _run(grid, frontier_escalate_iters)),
            )
        elif self.mesh is not None:
            # Mesh-parallel bucket programs (ISSUE 8): the SAME packed-row
            # contract and traced iteration budget, shard_mapped over the
            # mesh's data axis so one bucket batch splits across every
            # device (parallel/shard.make_packed_serving_program — one
            # memoized implementation shared with the multi-host serving
            # loop's global-mesh fan-out). waves follows the GLOBAL bucket
            # width (always >1 here — buckets are mesh-rounded), matching
            # what the single-device program would trace for the same
            # width, so work counters stay parity-comparable.
            from .parallel.shard import make_packed_serving_program

            self._program = make_packed_serving_program(
                self.mesh,
                self.spec,
                max_depth=self.max_depth,
                locked_candidates=self.locked_candidates,
                waves=self.waves,
                naked_pairs=self.naked_pairs,
                solver_overrides=tuple(
                    sorted(self.solver_overrides.items())
                ),
            )
            self._solve = lambda grid: self._exec(grid, self.max_iters)
            self._solve_deep = lambda grid: self._exec(
                grid, self.max_iters * self.deep_retry_factor
            )
            self._solve_quick = lambda grid: self._exec(
                grid, self.frontier_escalate_iters
            )
        else:
            # ONE parameterized program per bucket width: the lockstep
            # loop only ever COMPARES iters against max_iters
            # (ops/solver.py while/cond predicates), so the budget can be
            # a traced scalar — the RUNNING-safety-net deep retry and the
            # auto-route quick probe then share the normal path's compiled
            # executable instead of each paying its own trace+compile.
            # 3 programs per bucket -> 1; program_count() measures it.
            self._program = jax.jit(_run)
            self._solve = lambda grid: self._exec(grid, self.max_iters)
            self._solve_deep = lambda grid: self._exec(
                grid, self.max_iters * self.deep_retry_factor
            )
            self._solve_quick = lambda grid: self._exec(
                grid, self.frontier_escalate_iters
            )

        # the handoff probe (frontier_handoff, xla backend only): the same
        # short budget, but returning the full DFS state so an escalated
        # board's race seeds from the probe's UNEXPLORED subtrees instead
        # of restarting at the root (VERDICT r3 task 6 — the auto-route
        # double-pay). Flat depth = the race's collapsed depth, so the
        # handed-off stack decomposition matches what the race would
        # guarantee (parallel/frontier.state_handoff_frontier).
        depth_flat = self.max_depth
        if isinstance(depth_flat, (tuple, list)):
            depth_flat = max(depth_flat)

        def _run_quick_state(grid):
            st = _solver.init_state(grid, self.spec, depth_flat)

            def cond(s):
                return ((s.status == RUNNING).any()) & (
                    s.iters < frontier_escalate_iters
                )

            # the probe traces with the same loop flavor as the bucket
            # programs: --solver-config=legacy means legacy end to end
            _packed, _legacy = self._loop_flavor()
            st = jax.lax.while_loop(
                cond,
                lambda s: _solver.step(
                    s,
                    self.spec,
                    self.locked_candidates,
                    1,  # waves_eff for a single board (see _run)
                    naked_pairs=self.naked_pairs,
                    packed=_packed,
                    legacy_merges=_legacy,
                ),
                st,
            )
            st = _solver.finalize_status(st, self.spec)
            # packed row for the common (probe-answers-it) path — ONE
            # device→host transfer, the same serving contract as _run; the
            # full state rides along untouched and is only fetched when
            # the request escalates (code-review r4)
            packed = jnp.concatenate(
                [
                    st.grid[0],
                    st.status[:1],
                    st.guesses[:1],
                    st.validations[:1],
                ]
            )
            return packed, st

        self._solve_quick_state = jax.jit(_run_quick_state)

        # Continuous-batching segment program (ISSUE 12): state-in /
        # state-out, the segment budget a TRACED scalar (the PR 4 move),
        # so every segment of every length shares ONE executable per pool
        # width. Flat stack depth — segments resume mid-search, so the
        # staged shallow/deep trick cannot apply (same collapse as the
        # frontier racer).
        self._depth_flat = depth_flat
        if self.backend != "xla":
            self._segment_program = None
        elif self.mesh is not None:
            from .parallel.shard import make_segment_serving_program

            self._segment_program = make_segment_serving_program(
                self.mesh,
                self.spec,
                max_depth=depth_flat,
                locked_candidates=self.locked_candidates,
                waves=self.waves,
                naked_pairs=self.naked_pairs,
                solver_overrides=tuple(
                    sorted(self.solver_overrides.items())
                ),
                pipeline=self.segment_pipeline,
            )
        elif self.segment_pipeline:
            # Pipelined arm (PR 15): source-indexed injection, the
            # carried SegmentState DONATED (the (width, D, C) stack — the
            # state's bulk — updates in place instead of copying every
            # segment; the input handle is dead after dispatch, guarded
            # at the seam in dispatch_segment), and the outputs split
            # into the compact per-lane digest fetched every boundary
            # plus the prefix-gathered solution block fetched only when
            # a lane newly solved (ops/solver.segment_digest). boards/
            # src are NOT donated — the driver reuses its cached idle
            # argument pair across segments.
            def _run_segment_prog_pipelined(state, boards, src, seg_iters):
                from .ops.config import segment_prefix_gather
                from .ops.solver import (
                    inject_lanes_src,
                    run_segment,
                    segment_digest,
                )

                B = boards.shape[0]
                waves_eff = 1 if B == 1 else self.waves
                _packed, _legacy = self._loop_flavor()
                state = inject_lanes_src(state, boards, src, self.spec)
                entry_running = state.status == RUNNING
                state, lstats = run_segment(
                    state, seg_iters, self.spec,
                    locked_candidates=self.locked_candidates,
                    waves=waves_eff, naked_pairs=self.naked_pairs,
                    packed=_packed, legacy_merges=_legacy,
                )
                digest, gathered = segment_digest(
                    state, entry_running, lstats,
                    # trace-time form choice from the pool's STATIC
                    # byte size — the ONE shared predicate, so the
                    # host-side fetch agrees by construction
                    prefix_gather=segment_prefix_gather(
                        B, self.spec.cells
                    ),
                )
                return state, digest, gathered

            self._segment_program = jax.jit(
                _run_segment_prog_pipelined, donate_argnums=(0,)
            )
        else:
            def _run_segment_prog(state, boards, inject, seg_iters):
                from .ops.solver import inject_lanes, run_segment

                B = boards.shape[0]
                waves_eff = 1 if B == 1 else self.waves
                _packed, _legacy = self._loop_flavor()
                state = inject_lanes(state, boards, inject, self.spec)
                state, lstats = run_segment(
                    state, seg_iters, self.spec,
                    locked_candidates=self.locked_candidates,
                    waves=waves_eff, naked_pairs=self.naked_pairs,
                    packed=_packed, legacy_merges=_legacy,
                )
                # packed segment rows, one transfer per segment (the
                # bucket-program contract plus a board_iters column):
                # [grid | solved | status | guesses | validations |
                #  board_iters | lane_steps | idle_lane_steps]
                rows = jnp.concatenate(
                    [
                        state.grid,
                        (state.status == SOLVED)[:, None].astype(jnp.int32),
                        state.status[:, None],
                        state.guesses[:, None],
                        state.validations[:, None],
                        state.board_iters[:, None],
                        jnp.broadcast_to(lstats.lane_steps, (B,))[:, None],
                        jnp.broadcast_to(
                            lstats.idle_lane_steps, (B,)
                        )[:, None],
                    ],
                    axis=1,
                )
                return state, rows

            self._segment_program = jax.jit(_run_segment_prog)

    @property
    def continuous_active(self) -> bool:
        """True when the coalesced path will ACTUALLY serve open-loop:
        the flag is on AND a local segment program exists AND no
        multi-host ``mesh_runner`` fan-out is wired (that path speaks the
        closed-loop (boards, iters) protocol). The /metrics block and the
        warmup plane key on this, not the bare flag."""
        return (
            self.continuous
            and self._segment_program is not None
            and self.mesh_runner is None
        )

    @property
    def frontier_enabled(self) -> bool:
        """True when single-board solves route through the frontier race
        (local mesh or multi-host serving loop)."""
        return self.frontier_mesh is not None or self.frontier_runner is not None

    @property
    def coalescer(self):
        """The engine's request coalescer, created (threads started) on
        first use so engines that never serve single-board traffic pay
        nothing. One per engine: the shared queue IS the batching."""
        if self._coalescer is None:
            with self._coalescer_init_lock:
                if self._coalescer is None:
                    from .parallel.coalescer import BatchCoalescer

                    wait_policy = None
                    if self.coalesce_adaptive:
                        from .serving.load import AdaptiveWaitPolicy

                        wait_policy = AdaptiveWaitPolicy(
                            max_wait_s=self.coalesce_max_wait_s,
                            quiescence_s=self.coalesce_quiescence_s,
                            burst_wait_s=self.coalesce_burst_wait_s,
                        )
                    self._coalescer = BatchCoalescer(
                        self,
                        max_wait_s=self.coalesce_max_wait_s,
                        quiescence_s=self.coalesce_quiescence_s,
                        burst_wait_s=self.coalesce_burst_wait_s,
                        inflight_depth=self.coalesce_inflight_depth,
                        max_batch=self.coalesce_max_batch,
                        wait_policy=wait_policy,
                        continuous=self.continuous,
                        deep_lane_cap=self.deep_lane_cap,
                    )
        return self._coalescer

    def close(self) -> None:
        """Drain and stop the coalescer (futures resolve before return)
        and the supervisor's watchdog when one is attached. Safe to call
        on an engine that never coalesced; idempotent."""
        if self._coalescer is not None:
            self._coalescer.close()
        if self.supervisor is not None:
            self.supervisor.close()

    def ready(self) -> bool:
        """Would ``/readyz`` pass: tier-0 warm AND — when a supervisor
        is attached — not LOST. ONE definition shared by the HTTP
        readiness route (net/http_api.readyz_route), the telemetry
        digest's ``ready`` field (obs/cluster.build_digest), and the
        autopilot's elastic-membership join gate
        (serving/autopilot.Autopilot.allow_join); a fourth hand-copy of
        this predicate would eventually disagree with the other three."""
        sup = self.supervisor
        return bool(self.warmed and not (sup is not None and sup.is_lost))

    def arm_device_trace(self, log_dir: str, calls: int = 4) -> None:
        """Arm the ``jax.profiler`` capture hook (CLI --device-trace-dir):
        the next warmup pass and the first ``calls`` supervised device
        dispatches each record an XLA trace into ``log_dir``. Idempotent
        re-arm: a later call resets the budget (the warmup capture stays
        once-only per process — one warmup is one artifact)."""
        with self._warm_lock:
            self.device_trace_dir = log_dir
            self._device_trace_budget = max(0, int(calls))

    def health(self) -> dict:
        """Operator-facing engine health, served under /metrics "engine".

        ``frontier_fallbacks`` counts /solve requests downgraded to the
        bucket path after a frontier failure; when the multi-host serving
        loop is attached its liveness and restart count ride along, so a
        dead loop is visible from the HTTP surface instead of only in logs.
        """
        out = {
            "backend": self.backend,
            "frontier_enabled": self.frontier_enabled,
            "frontier_route": self.frontier_route,
            "frontier_handoff": self.frontier_handoff,
            "frontier_fallbacks": self.frontier_fallbacks,
            "frontier_escalations": self.frontier_escalations,
            "coalesce": self.coalesce,
            # the continuous-batching arm (ISSUE 12): which loop shape the
            # coalesced path serves and its segment budget — the /metrics
            # evidence an A/B (bench.py --mode continuous) keys on
            "continuous": {
                # the ACTIVE state, not the flag: a multi-host leader
                # keeps the closed loop whatever the flag says
                "enabled": self.continuous_active,
                "configured": self.continuous,
                "segment_iters": self.segment_iters,
                # the pipelined boundary arm (PR 15): digest-only fetch
                # + donation + overlapped refill vs the PR 12 full-row
                # boundary (--no-segment-pipeline)
                "pipeline": self.segment_pipeline,
            },
            "warmed": self.warmed,
            "fully_warmed": self.fully_warmed,
            "warm": self.warm_info(),
        }
        # the device cost-accounting block (ISSUE 10, obs/cost.py):
        # per-bucket device-seconds / pps / fill / pad-waste split /
        # lane utilization, plus compile amortization against the warm
        # plane's recorded compile costs — /metrics "engine.cost"
        out["cost"] = self.cost.snapshot(warm_info=out["warm"])
        mesh = self.mesh_info()
        if mesh is not None:
            # the mesh-serving plane (ISSUE 8): topology + batch-split
            # counter evidence, the /metrics "engine.mesh" block
            out["mesh"] = mesh
        if self.supervisor is not None:
            # the one-word summary; the full state machine lives in the
            # /metrics top-level "health" block (supervisor.snapshot())
            out["supervisor"] = self.supervisor.state
        if self._coalescer is not None:
            out["coalescer"] = self._coalescer.stats()
        loop = self.frontier_loop
        if loop is None:
            # fallback: a bare bound FrontierServingLoop.solve as the runner
            loop = getattr(self.frontier_runner, "__self__", None)
        if loop is not None and hasattr(loop, "health"):
            for k, v in loop.health().items():
                out[f"frontier_loop_{k}"] = v
        return out

    # -- internals ---------------------------------------------------------
    def _note_program(self, name: str, width: int) -> None:
        """Record one distinct device program (first dispatch of this
        (variant, batch-width) pair) for the compile-cost counter."""
        key = (name, int(width))
        if key not in self._programs:
            with self._warm_lock:
                self._programs.add(key)

    def program_count(self) -> int:
        """Distinct device programs dispatched so far — the compile-cost
        measure the ISSUE-4 collapse is asserted on: a fully-warm xla
        engine holds len(buckets) programs (one per width; deep/quick
        budgets are traced arguments), plus one for the handoff probe
        when enabled."""
        with self._warm_lock:
            return len(self._programs)

    def _counted(self, name, fn):
        """Wrap a per-variant jit (pallas path) with program counting."""
        def call(grid):
            self._note_program(name, grid.shape[0])
            return fn(grid)

        return call

    def _exec(self, grid, iters: int):
        """Dispatch the shared bucket program (xla path): the iteration
        budget rides as a traced scalar, so normal/deep/quick calls on
        the same width hit ONE compiled executable. A verified AOT
        artifact for this width takes priority; an artifact that fails
        at dispatch time is dropped and the call re-runs on the jit path
        (never a correctness risk)."""
        self._note_program("solve", grid.shape[0])
        # only a few budget values ever occur (normal / deep / quick /
        # segment): memoize their device scalars so the hot path never
        # pays an extra host->device put per request
        it = self._iter_scalar(iters)
        exe = self._aot_execs.get(grid.shape[0])
        if exe is not None:
            try:
                return exe(grid, it)
            except Exception:  # noqa: BLE001 — artifact bad at runtime
                logger.exception(
                    "AOT executable (width %d) failed at dispatch — "
                    "dropping it, serving from the jit path",
                    grid.shape[0],
                )
                with self._warm_lock:
                    self._aot_execs.pop(grid.shape[0], None)
                    # keep warm_info honest: this width now serves from
                    # the jit path (whose compile the fallback dispatch
                    # below pays synchronously, once)
                    st = self._warm_state.get(grid.shape[0])
                    if st is not None:
                        st["source"] = "jit-fallback"
        return self._program(grid, it)

    def _tiling_active(self) -> bool:
        """True while a tiered warmup has left part of the ladder cold
        (mid-background-widen, or cut off by a warmup budget): bucket
        selection then prefers WARM widths and oversize batches tile over
        the largest warm width instead of paying a cold compile on the
        serving path. Engines that never called warmup() (or finished
        it) behave exactly as before."""
        return self._warmup_started and not self.fully_warmed

    def _warm_widths(self) -> list:
        with self._warm_lock:
            return sorted(
                b for b, st in self._warm_state.items() if st.get("warm")
            )

    def _bucket_for(self, n: int) -> int:
        # widths the supervisor quarantined (hung/failed programs) are
        # routed around — the next covering width serves instead; if
        # EVERY covering width is quarantined the original choice stands
        # (the caller's failure handling / fallback is the backstop, and
        # refusing to pick a bucket would be a new failure mode)
        quarantined = (
            self.supervisor.quarantined_widths()
            if self.supervisor is not None
            else ()
        )
        if self._tiling_active():
            warm = self._warm_widths()
            for b in warm:
                if n <= b and b not in quarantined:
                    return b
            # wider than every warm width: fall through to the cold
            # ladder (a direct dispatch can't tile — solve_batch_np
            # bounds its chunks by the largest warm width instead)
        for b in self.buckets:
            if n <= b and b not in quarantined:
                return b
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _device_batch(self, boards: np.ndarray) -> jnp.ndarray:
        arr = jnp.asarray(boards)
        if self.sharding is not None:
            arr = jax.device_put(arr, self.sharding)
        return arr

    def _dispatch_padded(self, boards: np.ndarray):
        """Pad ≤bucket boards into their bucket and launch ONE device call.

        Returns an opaque in-flight handle for ``_finalize_padded``. The
        device call is async-dispatched: this returns as soon as the program
        is enqueued, so a caller (the coalescer's dispatcher thread) can
        encode/pad batch N+1 on the host while batch N runs on device.

        THE supervised seam (serving/health.py): a watchdog token opens
        here and closes in ``_finalize_padded``, so the supervisor bounds
        the wall time of the whole dispatch→fetch span — and the
        engine-seam fault injector (utils/faults.EngineFaultInjector)
        plugs in at the same two points.
        """
        n = boards.shape[0]
        bucket = self._bucket_for(n)
        sup = self.supervisor
        token = sup.call_started(bucket) if sup is not None else None
        try:
            return (*self._dispatch_padded_inner(boards, bucket), token)
        except BaseException:
            if sup is not None:
                sup.call_finished(token, ok=False)
            raise

    def _dispatch_padded_inner(self, boards: np.ndarray, bucket: int):
        n = boards.shape[0]
        # dispatch wall-clock anchor: rides the handle so _finalize_padded
        # can bill the whole dispatch→fetch span to obs/cost.py
        t0 = time.monotonic()
        inj = self.fault_injector
        if inj is not None:
            inj.on_device_call(bucket)  # may raise (fail-next-N)
        if n < bucket:
            # Pad with a COPY of a real row, not empty boards: the lockstep
            # kernel runs until the slowest board in the bucket finishes,
            # and an empty board's full-blown DFS costs ~10× a typical
            # request (measured on CPU: 34 boards + 30 empty pads 150 ms vs
            # 64 real boards 13 ms in the same bucket-64 program) — pad
            # rows must never dominate the batch they ride in. A duplicate
            # of boards[0] adds zero extra iterations by construction.
            pad = np.broadcast_to(
                boards[0], (bucket - n, *boards.shape[1:])
            )
            boards = np.concatenate([boards, pad], axis=0)
        if self.mesh_runner is not None:
            # multi-host leader fan-out (parallel/serving_loop.py): the
            # padded bucket batch rides the SPMD loop's broadcast so every
            # pod host's devices enter the collective; returns host rows
            # (the loop's collective already synced), which _finalize_padded
            # passes through unchanged. Local profiling hooks don't apply —
            # the work runs inside the loop's round on every host.
            packed = self.mesh_runner(boards, int(self.max_iters))
            with self._lock:
                self.mesh_runner_dispatches += 1
            return packed, boards, n, t0
        if (
            self._device_trace_budget > 0
            and self.device_trace_dir is not None
            and self._profile_mutex.acquire(blocking=False)
        ):
            # --device-trace-dir capture (ISSUE 6 satellite): spend one
            # budgeted supervised-call capture. Budget re-checked under
            # _warm_lock — the lock-free pre-check above only gates the
            # mutex acquire, two racing dispatches must not both spend
            # the last slot.
            try:
                with self._warm_lock:
                    take = self._device_trace_budget > 0
                    if take:
                        self._device_trace_budget -= 1
                        self._device_trace_captured += 1
                if take:
                    with device_trace(self.device_trace_dir), annotate(
                        f"supervised_call_b{bucket}"
                    ):
                        packed = self._solve(self._device_batch(boards))
                else:
                    packed = self._solve(self._device_batch(boards))
            finally:
                self._profile_mutex.release()
        elif self.profile_dir is not None and self._profile_mutex.acquire(
            blocking=False
        ):
            try:
                with device_trace(self.profile_dir), annotate(
                    f"solve_bucket_{bucket}"
                ):
                    packed = self._solve(self._device_batch(boards))
            finally:
                self._profile_mutex.release()
        else:
            packed = self._solve(self._device_batch(boards))
        if self.mesh is not None:
            # batch-split evidence (sharding METADATA only — no transfer,
            # no sync on the in-flight call): how the dispatched batch
            # landed on the mesh, surfaced at mesh_info()/ /metrics
            from .parallel.shard import split_evidence

            split = split_evidence(packed)
            with self._lock:
                self.mesh_dispatches += 1
                self._mesh_last_split = split
                ndev = split.get("devices", 1)
                if (
                    self._mesh_min_devices is None
                    or ndev < self._mesh_min_devices
                ):
                    self._mesh_min_devices = ndev
        return packed, boards, n, t0

    def _finalize_padded(
        self, packed, boards: np.ndarray, n: int, t0=None, token=None
    ) -> np.ndarray:
        """Fetch an in-flight ``_dispatch_padded`` call (blocks on the
        device) and run the deep-retry safety net on any capped rows.
        ``t0`` is the dispatch's monotonic anchor (the cost-accounting
        span start) and ``token`` the supervision token the dispatch
        opened — both ride the opaque handle; the token closes here
        however the fetch ends.

        Returns the packed (n, C+6) host array: [grid | solved | status |
        guesses | validations | lane_steps | idle_lane_steps] per row
        (the two trailing columns are per-CALL LoopStats scalars
        broadcast across rows — obs/cost.py evidence, sliced off by
        every result reader).
        """
        sup = self.supervisor
        try:
            rows = self._finalize_padded_inner(packed, boards, n, t0)
        except BaseException:
            if sup is not None:
                sup.call_finished(token, ok=False)
            raise
        if sup is not None:
            sup.call_finished(token, ok=True)
        return rows

    def _record_call_cost(
        self, bucket: int, n: int, device_s, lane: int, idle: int,
        deep_retry: bool = False,
    ) -> None:
        """Fold one finalized device call into obs/cost.py, splitting the
        pad waste between the coalescer (rows short of the REQUESTED
        ladder width) and the mesh rounding (the extra width ISSUE 8's
        mesh-divisible ladder added on top)."""
        pad_total = bucket - n
        req_cover = next(
            (w for w in self.requested_buckets if w >= n), None
        )
        if req_cover is not None and req_cover <= bucket:
            pad_coalesce = req_cover - n
            pad_mesh = bucket - req_cover
        else:
            # the mesh-rounded width is NARROWER than any requested cover
            # (or n overflows the ladder): the rounding saved pad rows
            # rather than adding them — bill everything to the coalescer
            pad_coalesce = pad_total
            pad_mesh = 0
        self.cost.record_call(
            bucket=bucket,
            boards=n,
            pad_coalesce=pad_coalesce,
            pad_mesh=pad_mesh,
            device_s=device_s if device_s is not None else 0.0,
            lane_steps=lane,
            idle_lane_steps=idle,
            deep_retry=deep_retry,
        )

    def _finalize_padded_inner(
        self, packed, boards: np.ndarray, n: int, t0=None
    ) -> np.ndarray:
        inj = self.fault_injector
        if inj is not None:
            inj.on_fetch(boards.shape[0])  # may sleep (watchdog food)
        # THE documented sync point of the bucket path: exactly one
        # device→host transfer per dispatched batch, made explicit with
        # block_until_ready (analysis/jax_hygiene.py JAX101 contract).
        # np.array, not asarray: asarray of a jax Array is a READ-ONLY
        # view of the device buffer, and the deep-retry merge below
        # writes into the capped rows
        packed = np.array(jax.block_until_ready(packed))
        if inj is not None:
            packed = inj.corrupt(boards.shape[0], packed)
        C = self.spec.cells
        # cost accounting (obs/cost.py), BEFORE the deep-retry merge can
        # overwrite the trailing LoopStats columns of capped rows: the
        # whole dispatch→fetch wall, the real fill, and this call's
        # lane/idle counters (broadcast scalars — row 0 is the call's)
        self._record_call_cost(
            boards.shape[0],
            n,
            None if t0 is None else time.monotonic() - t0,
            int(packed[0, C + 4]) if packed.shape[1] > C + 4 else 0,
            int(packed[0, C + 5]) if packed.shape[1] > C + 5 else 0,
        )
        running = packed[:, C + 1] == RUNNING
        # trigger on REAL rows only: a deep pass for discarded pad lanes is
        # pure waste (the merge below may still overwrite pad rows — they
        # are sliced off either way)
        if running[:n].any():
            # Iteration-capped lanes (adversarial inputs only): one deep
            # retry instead of misreporting "no solution". Only the capped
            # boards rerun, re-packed into the smallest covering bucket —
            # one adversarial board in a 4096 bucket must not re-dispatch
            # all 4096 at deep_retry_factor× iterations (ADVICE r2). Work
            # counters accumulate across attempts like the staged-depth
            # retry. The deep program compiles lazily per bucket shape, as
            # before.
            capped = np.flatnonzero(running[:n])
            sub = boards[capped]
            bucket2 = self._bucket_for(len(capped))
            if len(capped) < bucket2:
                # same real-row padding rationale as _dispatch_padded —
                # here doubly so: the deep retry runs at deep_retry_factor×
                # the budget, and an empty-board pad could spin that whole
                # budget while every real lane sits finished
                sub = np.concatenate(
                    [
                        sub,
                        np.broadcast_to(
                            sub[0], (bucket2 - len(capped), *boards.shape[1:])
                        ),
                    ],
                    axis=0,
                )
            t_deep = time.monotonic()
            if self.mesh_runner is not None:
                # the deep retry is a collective too: it must ride the
                # loop like the first pass, or the leader would enter a
                # global program the followers never join
                deep = np.asarray(
                    self.mesh_runner(
                        sub, int(self.max_iters * self.deep_retry_factor)
                    )
                )
            else:
                deep = np.asarray(
                    jax.block_until_ready(
                        self._solve_deep(self._device_batch(sub))
                    )
                )
            # the deep retry is its own device call — its own cost sample
            self._record_call_cost(
                sub.shape[0],
                len(capped),
                time.monotonic() - t_deep,
                int(deep[0, C + 4]) if deep.shape[1] > C + 4 else 0,
                int(deep[0, C + 5]) if deep.shape[1] > C + 5 else 0,
                deep_retry=True,
            )
            first = packed[capped].copy()
            packed[capped] = deep[: len(capped)]
            packed[capped, C + 2] += first[:, C + 2]
            packed[capped, C + 3] += first[:, C + 3]
        return packed[:n]

    # -- continuous-batching segment seam (ISSUE 12) -----------------------
    def segment_pool_width(self) -> int:
        """The lane-pool width the continuous serving loop runs at: the
        bucket covering the coalescer's effective batch cap (mesh-rounded
        by the ladder, so refill always respects the mesh-divisible
        rounding)."""
        cap = min(
            self.coalesce_max_batch or self.buckets[-1], self.buckets[-1]
        )
        return self._bucket_for(cap)

    def new_segment_pool(self, width: int):
        """A fresh device-resident lane pool: every lane initialized from
        an instantly-UNSAT pad board (dead after one sweep, then a free
        slot). The pool state never round-trips to the host — segments
        carry it device-to-device; only the packed rows are fetched."""
        from .ops.solver import init_segment_state, pad_board

        N = self.spec.size
        boards = np.broadcast_to(
            np.asarray(pad_board(self.spec)), (width, N, N)
        )
        return init_segment_state(
            jnp.asarray(boards), self.spec, self._depth_flat
        )

    def dispatch_segment(
        self,
        state,
        boards: np.ndarray,
        inject: Optional[np.ndarray] = None,
        *,
        src: Optional[np.ndarray] = None,
        seg_iters: Optional[int] = None,
        injected: Optional[int] = None,
        pipelined: bool = False,
        boundary_host_s: float = 0.0,
    ) -> "_SegmentHandle":
        """Async half of one continuous-batching segment: open the
        supervision token, run the engine-seam fault injector's dispatch
        hook, enqueue the compiled segment program, and return an
        in-flight handle for :meth:`finalize_segment` — the segment-seam
        twin of ``_dispatch_padded``.

        Injection payload: the PR 12 arm takes the row-aligned ``inject``
        mask; the pipelined arm takes ``src`` (the per-lane source map of
        ``ops.solver.inject_lanes_src`` — ``-1`` no-op, ``-2`` pad
        re-seed, else a ``boards`` row), and converts a mask to the
        identity map when only ``inject`` is given so library/test
        callers work on both arms.

        DONATION SEAM GUARD (pipelined arm): the passed ``state`` is
        consumed by this call — its buffers are donated to the program
        and the handle's ``state`` is the only live pool afterwards. A
        caller that passes an already-donated state (any error path must
        REBUILD the pool, never retry with a dead handle) gets a
        RuntimeError here instead of a deep XLA "Array has been deleted"
        from an arbitrary later op.

        ``pipelined=True`` marks a speculative dispatch issued while the
        previous segment's digest is still unfetched: its supervision
        token is sized at 2× the watchdog budget because its dispatch→
        fetch span legitimately includes the whole segment ahead of it
        in the device queue (serving/health.py ``budget_scale``).

        ``boundary_host_s`` is the host-side gap since the previous
        segment's digest fetch completed — the device-idle window the
        pipelined driver exists to close, stamped into obs/cost.py at
        finalize (0 for speculative dispatches: they overlap by
        construction).
        """
        width = boards.shape[0]
        if self.segment_pipeline and state is not None:
            g = getattr(state, "grid", None)
            deleted = getattr(g, "is_deleted", None)
            if deleted is not None and deleted():
                raise RuntimeError(
                    "segment pool state was already donated to an "
                    "earlier dispatch — a failed or superseded segment "
                    "must rebuild the pool (new_segment_pool), never "
                    "reuse a donated handle"
                )
        sup = self.supervisor
        token = (
            sup.call_started(width, budget_scale=2.0 if pipelined else 1.0)
            if sup is not None
            else None
        )
        t0 = time.monotonic()
        try:
            inj = self.fault_injector
            if inj is not None:
                inj.on_device_call(width)  # may raise (fail-next-N)
            self._note_program("segment", width)
            # callers may pass device-resident boards/src (the driver
            # caches the idle no-injection pair and the prestager places
            # the refill stack while the previous segment runs):
            # converting 2 KB of numpy per segment costs more than the
            # whole digest fetch at CPU serving widths, so skip it when
            # already placed
            if not isinstance(boards, jax.Array):
                boards = self._device_batch(boards)
            it = self._iter_scalar(
                int(seg_iters) if seg_iters else self.segment_iters
            )
            if self.segment_pipeline:
                if src is None:
                    # mask → identity source map (row i injects lane i):
                    # the library/test compatibility shim
                    mask = np.asarray(inject).astype(bool)
                    src = np.where(
                        mask, np.arange(width, dtype=np.int32),
                        np.int32(-1),
                    )
                if isinstance(src, jax.Array):
                    src_dev = src
                    if injected is None:
                        injected = int(
                            (
                                np.asarray(jax.block_until_ready(src_dev))
                                >= 0
                            ).sum()
                        )
                else:
                    src_np = np.asarray(src, np.int32)
                    if injected is None:
                        # real requests only: -2 pad re-seeds of
                        # abandoned lanes are not injections
                        injected = int((src_np >= 0).sum())
                    src_dev = jnp.asarray(src_np, jnp.int32)
                state, digest, gathered = self._segment_program(
                    state, boards, src_dev, it
                )
                rows_dev = None
                evidence = digest
            else:
                if inject is None:
                    raise ValueError(
                        "the PR 12 segment arm takes an inject mask — "
                        "src= needs segment_pipeline=True"
                    )
                if isinstance(inject, jax.Array):
                    inject_dev = inject
                    if injected is None:
                        # count injections from a settled host copy — an
                        # eight-int fetch of a mask host-built moments ago
                        injected = int(
                            np.asarray(jax.block_until_ready(inject_dev))
                            .astype(bool).sum()
                        )
                else:
                    inject_np = np.asarray(inject)
                    if injected is None:
                        injected = int(inject_np.astype(bool).sum())
                    inject_dev = jnp.asarray(inject_np, jnp.int32)
                state, rows_dev = self._segment_program(
                    state, boards, inject_dev, it
                )
                digest = gathered = None
                evidence = rows_dev
            if self.mesh is not None:
                from .parallel.shard import split_evidence

                split = split_evidence(evidence)
                with self._lock:
                    self.mesh_dispatches += 1
                    self._mesh_last_split = split
                    ndev = split.get("devices", 1)
                    if (
                        self._mesh_min_devices is None
                        or ndev < self._mesh_min_devices
                    ):
                        self._mesh_min_devices = ndev
        except BaseException:
            if sup is not None:
                sup.call_finished(token, ok=False)
            raise
        return _SegmentHandle(
            state=state,
            digest=digest,
            gathered=gathered,
            rows=rows_dev,
            token=token,
            t0=t0,
            width=width,
            injected=int(injected),
            pipelined=bool(pipelined),
            boundary_host_s=float(boundary_host_s),
        )

    def finalize_segment(self, handle: "_SegmentHandle", *, active):
        """Blocking half: fetch the boundary bytes, close the
        supervision token, and stamp the segment into obs/cost.py.

        Pipelined arm — the TWO-PHASE fetch: phase 1 moves only the
        (width, SEGMENT_DIGEST_COLS) int32 digest; when any lane's
        ``fetch_slot`` is set (it newly solved this segment), phase 2
        fetches the prefix of the on-device gathered solution block
        covering exactly those lanes. The returned ``rows`` keep the
        PR 12 (width, C+7) packed layout — grid columns are zero for
        lanes whose solution was never fetched (never needed: the driver
        reads grids only for newly-solved lanes) — so every downstream
        reader (``_row_result``, ``_account_coalesced``, the deep-retry
        counter merge) is arm-agnostic.

        ``active`` is the (width,) bool mask of lanes holding a live
        request at FETCH time — for a speculative dispatch the driver's
        slot table may have resolved lanes since dispatch, and the
        fill/utilization denominators should reflect that.

        Returns ``(rows, device_s)``; the carried pool state is on the
        handle (it was available at dispatch — that is the point).
        """
        sup = self.supervisor
        width = handle.width
        fetch_bytes = 0
        C = self.spec.cells
        try:
            inj = self.fault_injector
            if inj is not None:
                inj.on_fetch(width)  # may sleep (watchdog food)
            if handle.rows is not None:
                # PR 12 arm: the full packed rows, one transfer —
                # byte-for-byte the --no-segment-pipeline boundary
                rows = np.array(jax.block_until_ready(handle.rows))
                fetch_bytes = rows.nbytes
            else:
                digest = np.array(jax.block_until_ready(handle.digest))
                fetch_bytes = digest.nbytes
                rows = np.zeros((width, C + 7), np.int32)
                rows[:, C] = digest[:, 1]       # solved
                rows[:, C + 1] = digest[:, 0]   # status
                rows[:, C + 2] = digest[:, 2]   # guesses
                rows[:, C + 3] = digest[:, 3]   # validations
                rows[:, C + 4] = digest[:, 4]   # board_iters
                rows[:, C + 5] = digest[:, 6]   # lane_steps
                rows[:, C + 6] = digest[:, 7]   # idle_lane_steps
                slots = digest[:, 5]
                lanes = np.nonzero(slots >= 0)[0]
                if lanes.size:
                    # phase 2: fetch the solution block. Large pools
                    # slice the contiguous newly-solved prefix (bytes
                    # proportional to finished lanes); small pools copy
                    # the whole materialized block — the eager slice op
                    # costs ~100× the bytes it saves there. The SAME
                    # predicate the program traced with, so the host
                    # reads the block exactly as the device built it
                    # (segment_digest prefix_gather rationale)
                    from .ops.config import segment_prefix_gather

                    n = int(slots[lanes].max()) + 1
                    if segment_prefix_gather(width, C):
                        grids = np.array(
                            jax.block_until_ready(handle.gathered[:n])
                        )
                    else:
                        grids = np.array(
                            jax.block_until_ready(handle.gathered)
                        )
                    fetch_bytes += grids.nbytes
                    rows[lanes, :C] = grids[slots[lanes]]
            if inj is not None:
                rows = inj.corrupt(width, rows)
        except BaseException:
            if sup is not None:
                sup.call_finished(handle.token, ok=False)
            raise
        if sup is not None:
            sup.call_finished(handle.token, ok=True)
        device_s = time.monotonic() - handle.t0
        act = np.asarray(active, bool)
        self.cost.note_segment(
            width=width,
            active=int(act.sum()),
            injected=handle.injected,
            resolved=int(((rows[:, C + 1] != RUNNING) & act).sum()),
            device_s=device_s,
            lane_steps=int(rows[0, C + 5]) if rows.shape[1] > C + 5 else 0,
            idle_lane_steps=(
                int(rows[0, C + 6]) if rows.shape[1] > C + 6 else 0
            ),
            pipelined=handle.pipelined,
            boundary_host_s=handle.boundary_host_s,
            fetch_bytes=fetch_bytes,
        )
        return rows, device_s

    def abandon_segment(self, handle: "_SegmentHandle") -> None:
        """Discard a dispatched-but-never-fetched segment (the pipelined
        driver throws its speculative dispatch away when the segment
        ahead of it failed — the donated pool state is suspect either
        way and gets rebuilt). Closes the supervision token WITHOUT
        feeding the breaker in either direction: an unfetched segment
        proves nothing about the device, and double-counting the
        failure that caused the abandonment would double-step the
        breaker toward LOST."""
        sup = self.supervisor
        if sup is not None:
            sup.call_abandoned(handle.token)

    def run_segment_supervised(
        self,
        state,
        boards: np.ndarray,
        inject: np.ndarray,
        *,
        active: np.ndarray,
        seg_iters: Optional[int] = None,
        injected: Optional[int] = None,
        boundary_host_s: float = 0.0,
    ):
        """One continuous-batching segment through THE supervised seam:
        a watchdog token opens around the dispatch→fetch span (the PR 5
        contract, same as ``_dispatch_padded``/``_finalize_padded``), the
        engine-seam fault injector plugs in at the same two points, and
        the segment's device wall / lane counters are stamped into
        obs/cost.py — one locked append per SEGMENT, never per request.

        Synchronous composition of ``dispatch_segment`` +
        ``finalize_segment`` (the pipelined driver runs the two phases
        itself so segment N+1 can dispatch before segment N's digest is
        read); works on BOTH boundary arms — the pipelined engine
        converts the ``inject`` mask to an identity source map.

        ``active`` is the (width,) bool mask of lanes holding a live
        request AFTER this boundary's injections — the fill/utilization
        denominators, and which lanes count as "resolved" when terminal.

        ``seg_iters`` overrides this segment's iteration budget (None →
        the engine's configured k). The budget is a traced ARGUMENT of
        the one compiled segment program, so the driver's geometric
        escalation on all-deep pools costs zero compiles.

        ``injected`` is the number of REAL requests boarding this
        segment (the driver's refill count) — the cost plane's
        ``injected`` gauge must reconcile with ``resolved``, so pad
        re-seeds of abandoned lanes are excluded. None (library/test
        callers) falls back to counting the mask.

        Returns ``(state, rows, device_s)``: the carried-forward
        device-resident pool state, the fetched (width, C+7) packed host
        rows, and the segment's dispatch→fetch wall time (the riders'
        per-segment device-stage stamp).
        """
        handle = self.dispatch_segment(
            state, boards, inject, seg_iters=seg_iters, injected=injected,
            boundary_host_s=boundary_host_s,
        )
        rows, device_s = self.finalize_segment(handle, active=active)
        return handle.state, rows, device_s

    def _iter_scalar(self, iters: int):
        """Memoized device scalar for a traced iteration budget (shared
        with ``_exec`` — benign double-create race stores equal values)."""
        it = self._iter_scalars.get(iters)
        if it is None:
            it = jnp.int32(iters)
            self._iter_scalars[iters] = it
        return it

    def _solve_padded(self, boards: np.ndarray) -> np.ndarray:
        """Solve ≤bucket boards, padding with duplicates of the first row.

        Synchronous composition of ``_dispatch_padded`` + ``_finalize_padded``
        (the coalescer runs the two phases on separate threads instead).
        Runs in the requesting thread, so the caller's request span (when
        one is open — the --no-coalesce /solve path, /solve_batch chunks)
        accumulates the call's wall time as device stage here; coalesced
        requests are stamped by the coalescer's threads instead.
        """
        tr = current_trace()
        if tr is None:
            return self._finalize_padded(*self._dispatch_padded(boards))
        t0 = time.monotonic()
        try:
            rows = self._finalize_padded(*self._dispatch_padded(boards))
        finally:
            tr.mark("device", time.monotonic() - t0)
        tr.bucket = self._bucket_for(boards.shape[0])
        return rows

    def _account_coalesced(self, rows: np.ndarray) -> None:
        """Fold one coalesced batch's work into the engine counters — the
        same accounting ``solve_batch_np`` does for its callers."""
        C = self.spec.cells
        with self._lock:
            self.validations += int(rows[:, C + 3].sum())
            self.solved_puzzles += int(rows[:, C].sum())

    def _row_result(self, row: np.ndarray, routed: str = "coalesced"):
        """One packed host row → the (solution | None, info) contract of
        ``solve_one``. ``capped`` keeps the not-finished ≠ proven-UNSAT
        distinction (the deep retry already ran in _finalize_padded; on
        the continuous path the segment driver runs it before resolving
        and passes ``routed='continuous'``)."""
        C = self.spec.cells
        N = self.spec.size
        solved = bool(row[C])
        info = {
            "validations": int(row[C + 3]),
            "guesses": int(row[C + 2]),
            "capped": int(row[C + 1] == RUNNING),
            "routed": routed,
        }
        solution = row[:C].reshape(N, N).tolist() if solved else None
        return solution, info

    # -- public API --------------------------------------------------------
    def warmup(
        self,
        *,
        budget_s: Optional[float] = None,
        background: bool = False,
    ) -> None:
        """Pre-compile the serving programs, tiered (first TPU compile is
        ~seconds to ~minutes; serving must never pay it — reference
        node.py has the same issue in spirit: its first request is as
        slow as every other).

        Tier 0 — compiled synchronously, budget-exempt: the smallest
        bucket, the coalescer's preferred width (its max_batch cap), and
        the auto-route probe program — exactly what one ``/solve``
        needs. ``warmed`` flips there: the node is servable. The rest of
        the ladder (and the frontier race rungs) then widens — inline by
        default, so a bare ``warmup()`` still returns fully warm exactly
        as before, or in a daemon thread with ``background=True``.

        ``budget_s`` bounds the WIDENING (a short TPU claim window
        spends its seconds on the buckets the bench will hit): buckets
        that would start past the budget are skipped (listed in
        ``warm_info()["skipped"]``), ``fully_warmed`` stays False, and
        oversize requests tile over the largest warm width instead of
        paying a cold compile (``_bucket_for``/``solve_batch_np``). A
        later ``warmup()`` call resumes where the budget cut off.

        With a ``compile_cache_dir``, each bucket loads from a verified
        AOT artifact when one matches this backend (compilecache/), else
        compiles — hitting the persistent XLA cache when possible — and
        saves the executable back for the next cold start.
        """
        deadline = None if budget_s is None else time.monotonic() + budget_s
        with self._warm_lock:
            self._warmup_started = True
            # --device-trace-dir capture (ISSUE 6 satellite): the first
            # warmup pass records its tier-0 compiles+solves as an XLA
            # trace — once per process, and only if no other trace is
            # live (the profiler allows one active trace per process)
            trace_warm = (
                self.device_trace_dir is not None
                and not self._warmup_trace_done
            )
        trace_warm = trace_warm and self._profile_mutex.acquire(
            blocking=False
        )
        try:
            with contextlib.ExitStack() as stack:
                if trace_warm:
                    with self._warm_lock:
                        self._warmup_trace_done = True
                    stack.enter_context(device_trace(self.device_trace_dir))
                    stack.enter_context(annotate("warmup_tier0"))
                for b in self._tier0_buckets():
                    self._warm_bucket(b)
                self._warm_probe_programs()
                self._warm_segment_program()
        finally:
            if trace_warm:
                self._profile_mutex.release()
        with self._warm_lock:
            self.warmed = True
        if background:
            t = threading.Thread(
                target=self._warm_widen,
                args=(deadline,),
                name="engine-warmup",
                daemon=True,
            )
            self._warm_thread = t
            t.start()
            return
        self._warm_widen(deadline)

    def _tier0_buckets(self) -> list:
        """The widths one ``/solve`` needs hot before anything else: the
        smallest bucket (every lone request) and, when the coalescer runs
        with an explicit ``max_batch`` cap, the width its batches
        actually dispatch at."""
        tier = {self.buckets[0]}
        if self.coalesce and self.coalesce_max_batch:
            cap = min(self.coalesce_max_batch, self.buckets[-1])
            for b in self.buckets:
                if cap <= b:
                    tier.add(b)
                    break
        return sorted(tier)

    def _warm_probe_programs(self) -> None:
        """Tier-0 companion: the auto-route probe a frontier engine runs
        before every routing decision. On the xla path the quick probe
        shares the bucket program (its budget is a traced argument) — it
        is already warm with tier 0; only the handoff state probe (its
        own output signature) and the pallas quick variant compile
        separately."""
        if not (self.frontier_enabled and self.frontier_route == "auto"):
            return
        N = self.spec.size
        if (
            self.frontier_handoff
            and self.frontier_runner is None
            and self.backend == "xla"
        ):
            # plain transfer, matching _probe_quick_state (no batch
            # sharding for a 1-row probe array)
            self._note_program("quick_state", 1)
            jax.block_until_ready(
                self._solve_quick_state(
                    jnp.asarray(np.zeros((1, N, N), np.int32))
                )
            )
        elif self._program is None:
            b1 = self._bucket_for(1)
            jax.block_until_ready(
                self._solve_quick(
                    self._device_batch(np.zeros((b1, N, N), np.int32))
                )
            )

    def _warm_segment_program(self) -> None:
        """Tier-0 companion for the continuous serving loop (ISSUE 12):
        compile the segment program at the pool width before serving —
        the first /solve must never pay its trace, and the supervisor's
        LOST-rebuild warmup re-proves it the same way. One trivial
        segment over an all-pad pool (instantly-UNSAT lanes, dead in one
        sweep) is the whole cost."""
        if not self.continuous_active:
            return
        w = self.segment_pool_width()
        N = self.spec.size
        state = self.new_segment_pool(w)
        self._note_program("segment", w)
        if self.segment_pipeline:
            # the pipelined program's injection payload is a source map
            # (-1 = no injection), and the warm state is consumed by
            # donation — rebind it (the JAX105 carried-state contract)
            # and prove the trace through the digest output
            state, digest, _gathered = self._segment_program(
                state,
                self._device_batch(np.zeros((w, N, N), np.int32)),
                jnp.full((w,), -1, jnp.int32),
                self._iter_scalar(self.segment_iters),
            )
            jax.block_until_ready(digest)
        else:
            _state, packed = self._segment_program(
                state,
                self._device_batch(np.zeros((w, N, N), np.int32)),
                jnp.zeros((w,), jnp.int32),
                self._iter_scalar(self.segment_iters),
            )
            jax.block_until_ready(packed)

    def _warm_bucket(self, b: int) -> None:
        """Compile (or AOT-load) the width-``b`` bucket program and record
        it warm. Idempotent. The AOT path never raises — trace-and-compile
        through the jit cache is the fallback of last resort."""
        with self._warm_lock:
            if self._warm_state.get(b, {}).get("warm"):
                return
        N = self.spec.size
        t0 = time.perf_counter()
        source = "jit"
        if self._aot_store is not None and self._program is not None:
            exe, source = self._aot_load_or_compile(b)
            if exe is not None:
                self._note_program("solve", b)
                with self._warm_lock:
                    self._aot_execs[b] = exe
                    self._warm_state[b] = {
                        "warm": True,
                        "source": source,
                        "compile_s": round(time.perf_counter() - t0, 3),
                    }
                    self._warm_order.append(b)
                return
            source = "jit"  # the store failed end to end: plain compile
        jax.block_until_ready(
            self._solve(self._device_batch(np.zeros((b, N, N), np.int32)))
        )
        with self._warm_lock:
            self._warm_state[b] = {
                "warm": True,
                "source": source,
                "compile_s": round(time.perf_counter() - t0, 3),
            }
            self._warm_order.append(b)

    def _program_config(self) -> dict:
        """Every solver knob baked into the bucket program's trace — the
        AOT artifact key's config component. ``max_iters`` and the probe
        budget are absent on purpose: they are traced ARGUMENTS of the
        shared program, not trace constants."""
        cfg = {
            "backend": self.backend,
            "max_depth": self.max_depth,
            "locked_candidates": self.locked_candidates,
            "waves": self.waves,
            "naked_pairs": self.naked_pairs,
            # packed-row format version: v2 = two trailing LoopStats
            # columns (ISSUE 10 cost accounting) — keys a clean artifact
            # break instead of a load-then-fail-shape-verify round trip
            "row_format": "v2-lanestats",
        }
        if self.backend == "xla":
            # the RESOLVED hot-loop shape (ladder, period, packing, legacy
            # escape hatch) is part of the traced graph: a legacy-loop
            # engine must never load a default-loop artifact (functionally
            # identical, but an A/B would silently measure the wrong
            # program), and a changed default schedule must re-bake
            cfg["solver_loop"] = dict(
                sorted(self.solver_loop_info().items())
            )
            # the resolved continuous-batching arm (ISSUE 12): artifacts
            # baked by the open-loop serving plane must never load into a
            # closed-loop (--no-continuous) engine or across segment
            # shapes — an A/B would silently serve the wrong arm's plane
            cfg["segment"] = {
                "continuous": self.continuous,
                # the donated-arm program shape (PR 15): a donated
                # digest-program artifact must never load into a
                # --no-segment-pipeline engine (different signature AND
                # different aliasing contract) or vice versa; the
                # prefix-gather threshold is part of the traced form
                "pipeline": self.segment_pipeline,
                **self.segment_shape,
            }
            if self.segment_pipeline:
                from .ops.config import SEGMENT_PIPELINE

                cfg["segment"]["prefix_gather_min_bytes"] = (
                    SEGMENT_PIPELINE["prefix_gather_min_bytes"]
                )
        if self.mesh is not None:
            # the mesh SHAPE and sharding spec are trace constants of the
            # shard_map program: a 4-way split is a different program than
            # an 8-way one, and a single-device artifact must never load
            # into a sharded engine (ISSUE 8 — the PR 4 gap)
            cfg["mesh"] = {
                "axis": "data",
                "devices": int(self.mesh.devices.size),
                "spec": "P('data')",
            }
        return cfg

    def _aot_load_or_compile(self, b: int):
        """Returns (executable | None, source). Load path: artifact with
        a matching backend fingerprint, deserialized AND verified by one
        round-trip solve checked host-side against the sudoku rules — an
        artifact never serves before it has solved a board correctly.
        Compile path: explicit lower().compile() (a persistent-XLA-cache
        hit when the HLO was ever compiled here), saved back to the
        store for the next cold start."""
        from .compilecache import (
            backend_fingerprint,
            device_fingerprint,
            program_key,
        )

        key = program_key("solve", self.spec, b, self._program_config())
        fp = backend_fingerprint()
        # the exec tier's extra gate for sharded programs: a serialized
        # executable bakes which device holds which shard, so it is only
        # trusted on the exact ordered assignment that compiled it; the
        # StableHLO tier stays assignment-portable (compilecache/store.py)
        dev_fp = (
            device_fingerprint(self.mesh.devices.flat)
            if self.mesh is not None
            else None
        )
        exe, kind = self._aot_store.load(key, fp, device_fp=dev_fp)
        if exe is not None:
            if self._verify_aot(exe, b):
                return exe, f"aot:{kind}"
            # deserialized fine but solved WRONG (or crashed): poisoned
            # artifact — delete it so no later start trusts it either
            logger.warning(
                "AOT artifact for width %d failed round-trip verification"
                " — recompiling", b
            )
            self._aot_store.invalidate(key)
        try:
            N = self.spec.size
            # sharded programs lower against data-sharded input avals so
            # the compiled executable carries the mesh partitioning (and
            # the StableHLO export records it for the portable tier)
            if self.sharding is not None:
                board_aval = jax.ShapeDtypeStruct(
                    (b, N, N), jnp.int32, sharding=self.sharding
                )
            else:
                board_aval = jax.ShapeDtypeStruct((b, N, N), jnp.int32)
            avals = (board_aval, jax.ShapeDtypeStruct((), jnp.int32))
            compiled = self._program.lower(*avals).compile()
            stablehlo = None
            try:
                from jax import export as jax_export

                # the portable twin: costs one extra trace at BAKE time,
                # buys every backend that can't deserialize executables
                # (the CPU runtime here) a trace-free cold start
                stablehlo = jax_export.export(self._program)(
                    *avals
                ).serialize()
            except Exception:  # noqa: BLE001 — portable tier is optional
                logger.exception(
                    "jax.export of width-%d program failed — saving the "
                    "executable tier only", b
                )
            saved = self._aot_store.save(
                key,
                compiled,
                fp,
                meta={
                    "bucket": b,
                    "size": N,
                    "config": {
                        k: repr(v)
                        for k, v in self._program_config().items()
                    },
                },
                stablehlo=stablehlo,
                device_fp=dev_fp,
            )
            if saved:
                # bake-and-check: load the artifact back and round-trip
                # it NOW — a bake must never ship an artifact that can't
                # serve, and the check compiles the IR tier's module into
                # the persistent XLA cache so the next cold start's
                # aot:ir load is a disk hit instead of a fresh compile
                exe2, _kind2 = self._aot_store.load(key, fp, device_fp=dev_fp)
                if exe2 is None or not self._verify_aot(exe2, b):
                    logger.warning(
                        "just-saved AOT artifact for width %d failed its "
                        "round-trip — removing it", b
                    )
                    self._aot_store.invalidate(key)
            return compiled, "compile+save"
        except Exception:  # noqa: BLE001 — AOT is an optimization only
            logger.exception(
                "AOT lower/compile for width %d failed — jit fallback", b
            )
            return None, "jit"

    def _verify_aot(self, exe, b: int) -> bool:
        """One round-trip solve gates every artifact: the empty board
        must come back SOLVED with a grid that satisfies the sudoku
        rules, checked host-side — ground truth, stronger than comparing
        two executables' outputs. Any exception fails the artifact."""
        N = self.spec.size
        C = self.spec.cells
        try:
            # _device_batch, not a bare asarray: a sharded executable is
            # strict about its input placement — the probe batch must land
            # on the mesh exactly as serving batches do
            packed = np.asarray(
                jax.block_until_ready(
                    exe(
                        self._device_batch(np.zeros((b, N, N), np.int32)),
                        jnp.int32(self.max_iters),
                    )
                )
            )
        except Exception:  # noqa: BLE001 — a crashing artifact is invalid
            logger.exception("AOT artifact (width %d) failed to run", b)
            return False
        if packed.shape != (b, C + 6):
            # C+6 since ISSUE 10 (two trailing LoopStats columns) — a
            # pre-cost-plane artifact fails here and recompiles cleanly
            return False
        row = packed[0]
        if int(row[C + 1]) != SOLVED or not int(row[C]):
            return False
        # the repo's trusted host-side oracle (models/oracle.py) — the
        # same ground truth the test suite verifies the solver against
        from .models import oracle_is_valid_solution

        return oracle_is_valid_solution(row[:C].reshape(N, N).tolist())

    def _warm_widen(self, deadline: Optional[float]) -> None:
        """Widen past tier 0: the remaining buckets ascending, then the
        frontier race rungs. Runs inline (default) or as the background
        warm thread; a budget cut and a failure both leave the engine
        serving — tier-0 warm, cold widths tiled or compiled on
        demand."""
        try:
            for b in self.buckets:
                if deadline is not None and time.monotonic() > deadline:
                    skipped = [
                        x
                        for x in self.buckets
                        if not self._warm_state.get(x, {}).get("warm")
                    ]
                    with self._warm_lock:
                        self._warm_skipped = skipped
                    logger.info(
                        "warmup budget exhausted — skipping buckets %s "
                        "(serving tiles over the warm widths)",
                        skipped,
                    )
                    return
                self._warm_bucket(b)
            if self.frontier_mesh is not None:
                if deadline is not None and time.monotonic() > deadline:
                    with self._warm_lock:
                        self._warm_skipped = ["frontier"]
                    return
                self._warm_frontier()
            with self._warm_lock:
                self._warm_skipped = []
                self.fully_warmed = True
        except Exception:  # noqa: BLE001 — a failed widen must not kill serving
            logger.exception(
                "warmup widening failed — cold widths compile on demand"
            )

    def _warm_frontier(self) -> None:
        # compile the frontier race for the bucket ladder requests hit
        # in practice (seeding overshoots by a data-dependent factor ≤ N,
        # so frontier_solve pads to states_per_device × 2^k per device —
        # warm the first few rungs, raced on instantly-unsat pad states
        # so no counter or solution side effects; larger rungs compile
        # lazily on first hit). The direct racer call mirrors how bucket
        # warmup calls self._solve.
        from .parallel import frontier

        N = self.spec.size
        n_dev = self.frontier_mesh.devices.size
        target = n_dev * self.frontier_states_per_device
        frontier.warm_seeding(self.spec, target, self.locked_candidates)
        racer = frontier._make_racer(
            self.frontier_mesh,
            self.spec,
            frontier.DEFAULT_MAX_ITERS,
            self.max_depth,
            self.locked_candidates,
            self.waves,
            self.naked_pairs,
            *self._loop_flavor(),
        )
        for mult in (1, 2, 4):
            pad = np.broadcast_to(
                frontier._unsat_pad(self.spec), (target * mult, N, N)
            )
            np.asarray(racer(jnp.asarray(pad)))

    def _loop_flavor(self):
        """(packed, legacy_merges) for step-loop callers that trace outside
        solve_batch (the quick-state probe, the frontier race): the same
        --solver-config flavor the bucket programs run."""
        legacy = bool(self.solver_overrides.get("legacy_loop"))
        packed = False if legacy else self.solver_overrides.get("packed")
        return packed, legacy

    def solver_loop_info(self) -> dict:
        """The resolved hot-loop configuration this engine's bucket
        programs run (PR 7): compaction ladder (for the widest bucket),
        descent-check period, packed-bitplane state, and whether the
        legacy escape hatch is active. Rides warm_info()/ /metrics so a
        serving node's active schedule is observable."""
        if self.backend == "pallas":
            return {"backend": "pallas"}
        from .ops.config import resolved_loop_shape
        from .ops.solver import _compaction_schedule

        # THE same resolution the solver traces with (ops/config.py) — no
        # parallel re-derivation that could drift from the real schedule
        shape = resolved_loop_shape(self.spec.size, self.solver_overrides)
        return {
            "legacy": shape["legacy"],
            # packed planes only run inside locked sweeps; report the
            # bit that is actually live for this engine's config
            "packed": shape["packed"] and bool(self.locked_candidates),
            "compact_div": shape["div"],
            "compact_floor": shape["floor"],
            "compact_every": shape["every"],
            "ladder": _compaction_schedule(
                self.buckets[-1], shape["div"], shape["floor"]
            ),
        }

    def mesh_info(self) -> Optional[dict]:
        """The ``engine.mesh`` block of ``GET /metrics`` (ISSUE 8):
        resolved mesh topology, the bucket-ladder rounding it forced,
        per-device fill per bucket, and the batch-split counter evidence
        (device count + rows-per-device of the last dispatch, read from
        output sharding metadata). None when the engine has no mesh."""
        if self.mesh is None:
            return None
        n_dev = int(self.mesh.devices.size)
        with self._lock:
            last_split = dict(self._mesh_last_split)
            dispatches = self.mesh_dispatches
            runner_dispatches = self.mesh_runner_dispatches
            min_devices = self._mesh_min_devices
        return {
            "devices": n_dev,
            "axis": "data",
            "device_kinds": sorted(
                {d.device_kind for d in self.mesh.devices.flat}
            ),
            "buckets_requested": list(self.requested_buckets),
            "buckets": list(self.buckets),
            "per_device_fill": {str(b): b // n_dev for b in self.buckets},
            "dispatches": dispatches,
            "runner_dispatches": runner_dispatches,
            "last_split": last_split,
            "min_devices_seen": min_devices,
        }

    def warm_info(self) -> dict:
        """Per-bucket warm state (the /metrics ``engine.warm`` block):
        which widths are compiled and from what source (``aot`` /
        ``compile+save`` / ``jit``), tiered-warmup order, budget skips,
        the distinct-program count, and the AOT store's counters."""
        with self._warm_lock:
            out = {
                "warmed": self.warmed,
                "fully_warmed": self.fully_warmed,
                "tier0": self._tier0_buckets(),
                "buckets": {
                    str(b): dict(self._warm_state.get(b) or {"warm": False})
                    for b in self.buckets
                },
                "order": list(self._warm_order),
                "skipped": list(self._warm_skipped),
                "programs": len(self._programs),
                "solver_loop": self.solver_loop_info(),
            }
            if self.device_trace_dir is not None:
                # the --device-trace-dir capture state (ISSUE 6 satellite):
                # how many XLA trace artifacts this process has recorded
                # and how many supervised-call captures remain armed
                out["device_trace"] = {
                    "dir": self.device_trace_dir,
                    "warmup_traced": self._warmup_trace_done,
                    "captured_calls": self._device_trace_captured,
                    "calls_remaining": self._device_trace_budget,
                }
        if self._aot_store is not None:
            out["aot"] = self._aot_store.stats()
        # outside _warm_lock: mesh_info takes the engine stats lock and
        # the two must never nest (analysis/locks.py ordering discipline)
        mesh = self.mesh_info()
        if mesh is not None:
            out["mesh"] = mesh
        return out

    def solve_batch_np(self, boards: np.ndarray) -> Tuple[np.ndarray, np.ndarray, dict]:
        """Solve (B, N, N) boards.

        Returns (solutions, solved_mask, info). Solutions rows for unsolved
        boards hold the partial/original grid. Tiles over the largest bucket
        for oversize batches. ``info["capped"]`` counts boards whose search
        exhausted even the deep-retry iteration budget — for those "not
        solved" means "not finished", not "proven unsatisfiable".
        """
        boards = np.asarray(boards, np.int32)
        B = boards.shape[0]
        N = self.spec.size
        C = self.spec.cells
        cap = self.buckets[-1]
        if self._tiling_active():
            # mid-tiered-warmup (or budget-cut): tile over the largest
            # WARM width instead of compiling a rarely-hit cold bucket on
            # the serving path — the compile-cost half of ISSUE 4's
            # tiling item. Engines that never warmed (or finished) keep
            # the exact prior chunking.
            warm = self._warm_widths()
            if warm:
                cap = warm[-1]
        packed_rows = []
        for lo in range(0, B, cap):
            packed_rows.append(self._solve_padded(boards[lo : lo + cap]))
        packed = np.concatenate(packed_rows, axis=0)
        solutions = packed[:, :C].reshape(B, N, N)
        solved_mask = packed[:, C].astype(bool)
        validations = int(packed[:, C + 3].sum())
        guesses = int(packed[:, C + 2].sum())
        capped = int((packed[:, C + 1] == RUNNING).sum())
        with self._lock:
            self.validations += validations
            self.solved_puzzles += int(solved_mask.sum())
        return solutions, solved_mask, {
            "validations": validations,
            "guesses": guesses,
            "capped": capped,
        }

    def solve_batch_np_supervised(
        self, boards: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, dict]:
        """``solve_batch_np`` under the degraded-serving contract (ISSUE
        12 satellite — closing the PR 5 known limit on ``/solve_batch``):
        with a supervisor attached, an OPEN breaker routes every board
        through the supervised host-oracle fallback (bounded concurrency
        + per-board budget, serving/health.py) and a device failure
        mid-batch falls back the same way — the batch answers
        degraded-mode boards instead of a whole-batch error, exactly as
        the single-board path has since PR 5.

        ``info`` gains ``degraded_boards`` (per-board bools; the HTTP
        layer's body flags) and ``degraded`` (any-board summary → the
        ``X-Degraded`` response header). Without a supervisor this is
        byte-identical to ``solve_batch_np``.
        """
        boards = np.asarray(boards, np.int32)
        B = boards.shape[0]
        sup = self.supervisor
        if sup is None:
            return self.solve_batch_np(boards)
        if not sup.should_fallback():
            try:
                sols, mask, info = self.solve_batch_np(boards)
            except Exception:  # noqa: BLE001 — the seam already fed the breaker
                logger.exception(
                    "batch device path failed — answering per board from "
                    "the supervised oracle fallback"
                )
                return self._fallback_batch(sup, boards)
            info["degraded_boards"] = [False] * B
            info["degraded"] = False
            return sols, mask, info
        return self._fallback_batch(sup, boards)

    def _fallback_batch(self, sup, boards: np.ndarray):
        """Answer a whole batch from the supervised host oracle, board by
        board (bounded by the fallback semaphore; a board that trips the
        per-solve budget stays unsolved and counts as capped — "not
        finished", never a whole-batch 500)."""
        B = boards.shape[0]
        solutions = boards.copy()
        mask = np.zeros((B,), bool)
        capped = 0
        for i in range(B):
            try:
                sol, _info = sup.fallback_solve(boards[i])
            except Exception:  # noqa: BLE001 — budget trip or oracle failure
                capped += 1
                continue
            if sol is not None:
                solutions[i] = np.asarray(sol, np.int32)
                mask[i] = True
        with self._lock:
            self.solved_puzzles += int(mask.sum())
        return solutions, mask, {
            "validations": 0,
            "guesses": 0,
            "capped": capped,
            "degraded_boards": [True] * B,
            "degraded": True,
            "routed": "oracle-fallback",
        }

    def _probe_quick(self, arr: np.ndarray):
        """Auto-route probe: one bucket-1 pass at ``frontier_escalate_iters``.

        Returns (solution | None, info) when the probe FINISHED (solved, or
        proved unsatisfiable — both answer the request), or None when the
        board was still RUNNING at the budget: the deep-search tail that
        escalates to the frontier race (solve_one).
        """
        bucket = self._bucket_for(1)
        boards = arr[None]
        if bucket > 1:
            # pad with COPIES of the probe board, not empty boards: an
            # empty board's full DFS can dwarf the probe's own work, and
            # on a mesh engine the smallest bucket is the device count —
            # every probe would pay n_dev-1 empty-board solves (same
            # rationale as _dispatch_padded's real-row padding)
            boards = np.concatenate(
                [boards, np.broadcast_to(arr, (bucket - 1, *arr.shape))]
            )
        # explicit sync at the probe's documented fetch point (JAX101);
        # the probe IS device work — stamped on the request span so an
        # auto-routed /solve answers a non-zero X-Timing device field
        # whether the probe answered it or the race did (ISSUE 10
        # satellite: frontier-route span completeness)
        tr = current_trace()
        t_dev = time.monotonic()
        try:
            packed = np.asarray(
                jax.block_until_ready(
                    self._solve_quick(self._device_batch(boards))
                )
            )
        finally:
            if tr is not None:
                tr.mark("device", time.monotonic() - t_dev)
        C = self.spec.cells
        row = packed[0]
        status = int(row[C + 1])
        validations = int(row[C + 3])
        if status in (RUNNING, OVERFLOW):
            # RUNNING: out of probe iterations — the deep-search tail the
            # race exists for. OVERFLOW: the probe's guess stack overflowed,
            # which is NOT an answer either (with a custom int max_depth
            # shallower than the search needs, returning it as "no solution"
            # would be wrong — ADVICE r3); the race runs the full-depth
            # stack, so escalate both.
            with self._lock:
                # bill the probe's sweeps; the race accounts its own
                self.validations += validations
                self.frontier_escalations += 1
            return None
        solved = bool(row[C])
        with self._lock:
            self.validations += validations
            self.solved_puzzles += int(solved)
        info = {
            "validations": validations,
            "guesses": int(row[C + 2]),
            "routed": "bucket-quick",
        }
        N = self.spec.size
        return (row[:C].reshape(N, N).tolist() if solved else None), info

    def _probe_quick_state(self, arr: np.ndarray):
        """Handoff variant of ``_probe_quick`` (frontier_handoff=True).

        Returns ("done", (solution | None, info)) when the probe answered
        the request, or ("escalate", seed_states) with the probe's
        unexplored subtrees (parallel/frontier.state_handoff_frontier) for
        the race to continue from — the probe's search effort is handed
        off instead of re-paid (VERDICT r3 task 6)."""
        # plain device transfer, NOT _device_batch: a batch-axis sharding
        # can't place a 1-row array (K-way split of size 1), and _probe_quick
        # handles that case by bucket padding — here the state must stay
        # unpadded for the stack decomposition, so bypass the sharding (the
        # probe is a single-board program either way; code-review r4)
        self._note_program("quick_state", 1)
        tr = current_trace()
        t_dev = time.monotonic()
        try:
            packed_dev, st = self._solve_quick_state(jnp.asarray(arr[None]))
            # ONE transfer on the common path, explicit (JAX101); st stays
            # device-resident unless the request escalates
            packed = np.asarray(jax.block_until_ready(packed_dev))
        finally:
            if tr is not None:
                # same span-completeness contract as _probe_quick
                tr.mark("device", time.monotonic() - t_dev)
        C = self.spec.cells
        status = int(packed[C])
        validations = int(packed[C + 2])
        if status in (RUNNING, OVERFLOW):
            # same escalation contract as _probe_quick: neither is an
            # answer (OVERFLOW: see the staged-depth note there). Fetching
            # the stack here is the rare deep path; the race that follows
            # dominates the extra pulls.
            from .parallel.frontier import state_handoff_frontier

            seeds = state_handoff_frontier(jax.device_get(st), self.spec)
            with self._lock:
                self.validations += validations
                self.frontier_escalations += 1
            return "escalate", seeds
        solved = status == SOLVED
        with self._lock:
            self.validations += validations
            self.solved_puzzles += int(solved)
        info = {
            "validations": validations,
            "guesses": int(packed[C + 1]),
            "routed": "bucket-quick",
        }
        N = self.spec.size
        solution = (
            packed[:C].reshape(N, N).tolist() if solved else None
        )
        return "done", (solution, info)

    def _frontier_raw(self, arr: np.ndarray, seed_states=None, deadline_s=None):
        """Run the race without serving-stats side effects; _frontier_solve
        wraps it with the counter accounting.

        Deadline scope: the LOCAL race honors ``deadline_s`` at its
        seeding round boundaries and before dispatch (ISSUE 12). The
        multi-host ``frontier_runner`` path gets only the escalation-
        boundary check in ``solve_one`` — the serving loop's broadcast
        wire carries a bare board, so a deadline cannot follow the
        request across hosts yet (known limit; the round-trip is bounded
        by the loop's own timeout either way).

        Supervision + cost: the race opens a watchdog token under the
        sentinel width 0 (it is not a bucket program, but a hung mesh
        race must trip the same breaker the bucket seam feeds) with a
        scaled budget — a healthy race legitimately runs far past a
        single bucket call — and folds its wall time into
        ``cost.note_frontier`` on completion, so the frontier dispatch
        shape carries the supervision and cost legs of the dispatch
        contract (analysis/seams.py)."""
        from .serving.admission import DeadlineExceeded

        sup = self.supervisor
        token = (
            sup.call_started(0, budget_scale=8.0)
            if sup is not None
            else None
        )
        t0 = time.monotonic()
        try:
            if self.frontier_runner is not None:
                # multi-host race: the loop's round-trip IS this request's
                # device stage (the local branch is stamped finer inside
                # frontier_solve — seeding as coalesce, the race as device)
                tr = current_trace()
                t_dev = time.monotonic()
                try:
                    solution, info = self.frontier_runner(arr)
                finally:
                    if tr is not None:
                        tr.mark("device", time.monotonic() - t_dev)
            else:
                from .parallel import frontier_solve

                packed, legacy = self._loop_flavor()
                solution, info = frontier_solve(
                    arr,
                    self.frontier_mesh,
                    self.spec,
                    states_per_device=self.frontier_states_per_device,
                    max_depth=self.max_depth,
                    locked=self.locked_candidates,
                    waves=self.waves,
                    naked_pairs=self.naked_pairs,
                    packed=packed,
                    legacy_merges=legacy,
                    initial_states=seed_states,
                    deadline_s=deadline_s,
                )
        except DeadlineExceeded:
            # a policy abort proves nothing about the device: discard
            # without feeding the breaker either way
            if sup is not None:
                sup.call_abandoned(token)
            raise
        except BaseException:
            if sup is not None:
                sup.call_finished(token, ok=False)
            raise
        if sup is not None:
            sup.call_finished(token, ok=True)
        if self.cost is not None:
            self.cost.note_frontier(
                device_s=time.monotonic() - t0,
                escalated=seed_states is not None,
            )
        return solution, dict(info, frontier=True)

    def _frontier_solve(self, arr: np.ndarray, seed_states=None, deadline_s=None):
        solution, info = self._frontier_raw(arr, seed_states, deadline_s)
        with self._lock:
            self.validations += info["validations"]
            if solution is not None:
                self.solved_puzzles += 1
        return solution, info

    def solve_batch_resumable_np(
        self,
        boards: np.ndarray,
        checkpoint_path: str,
        *,
        chunk_iters: int = 256,
        max_iters: int = 65536,
        keep_checkpoint: bool = False,
    ) -> Tuple[np.ndarray, np.ndarray, dict]:
        """``solve_batch_np`` with crash durability: the solve advances in
        bounded chunks with an atomic .npz snapshot between chunks
        (utils/checkpoint.py), and a re-run with the same ``checkpoint_path``
        resumes bit-exact from the snapshot instead of restarting. For the
        long batches where a lost solve is expensive — the reference loses
        everything on a kill (SURVEY.md §5; its `pickle` import is dead code,
        reference node.py:11).

        Returns (solutions, solved_mask, info) like ``solve_batch_np``. The
        snapshot carries the per-board counters, so a resumed run folds the
        batch's *full* effort (pre-kill + post-resume) into this engine's
        stats — the killed process's in-RAM counters died with it, and the
        work must be attributed exactly once.
        """
        from .utils.checkpoint import solve_batch_resumable

        boards = np.asarray(boards, np.int32)
        # the mesh plane's data sharding only places mesh-divisible
        # batches; resumable solves take arbitrary B, so fall back to
        # default placement when the batch doesn't divide (explicit
        # sharding= callers keep the old contract: they sized their batch)
        sharding = self.sharding
        if self.mesh is not None and sharding is not None:
            if boards.shape[0] % int(self.mesh.devices.size):
                sharding = None
        res = solve_batch_resumable(
            boards,
            self.spec,
            checkpoint_path=checkpoint_path,
            chunk_iters=chunk_iters,
            max_iters=max_iters,
            max_depth=self.max_depth,
            keep_checkpoint=keep_checkpoint,
            sharding=sharding,
            locked=self.locked_candidates,
            waves=self.waves,
            naked_pairs=self.naked_pairs,
        )
        solved_mask = np.asarray(res.solved)
        validations = int(np.asarray(res.validations).sum())
        guesses = int(np.asarray(res.guesses).sum())
        with self._lock:
            self.validations += validations
            self.solved_puzzles += int(solved_mask.sum())
        return (
            np.asarray(res.grid),
            solved_mask,
            {"validations": validations, "guesses": guesses},
        )

    def solve_one(
        self,
        board: Sequence[Sequence[int]],
        *,
        frontier: Optional[bool] = None,
        deadline_s: Optional[float] = None,
    ) -> Tuple[Optional[List[List[int]]], dict]:
        """Solve a single board; returns (solution | None, info).

        With ``frontier_mesh`` configured, requests run the mesh-sharded
        subtree race instead of a bucket-1 batch solve. ``frontier=False``
        forces the bucket path for a single call — the P2P worker's per-cell
        tasks use it so farmed cells never occupy the whole mesh.

        ``deadline_s`` (absolute monotonic, the admission budget — ISSUE
        12 satellite): frontier-routed requests now honor it across the
        escalation leg, the contract the farm path got in PR 5 — a
        request that expires after its probe but before the race, or
        mid-seeding, raises ``DeadlineExceeded`` (the 429 path) instead
        of occupying the whole mesh for an answer nobody is waiting for.
        A race already dispatched runs to completion (service time paid
        is never thrown away)."""
        arr = np.asarray(board, np.int32)
        use_frontier = (
            self.frontier_enabled
            if frontier is None
            else (frontier and self.frontier_enabled)
        )
        seed_states = None
        if use_frontier and frontier is None and self.frontier_route == "auto":
            # measured routing policy (benchmarks/exp_frontier_crossover.py):
            # the quick bucket probe answers the easy mass in one short
            # device call; only boards still RUNNING at the escalation
            # budget — where serial search time dwarfs the race's seeding
            # overhead — go to the frontier. An explicit frontier=True
            # bypasses the probe.
            use_handoff = (
                self.frontier_handoff
                and self.frontier_runner is None
                and self.backend == "xla"
            )
            if use_handoff:
                outcome, payload = self._probe_quick_state(arr)
                if outcome == "done":
                    return payload
                seed_states = payload  # race continues the probe's search
            else:
                probed = self._probe_quick(arr)
                if probed is not None:
                    return probed
        if use_frontier:
            from .serving.admission import DeadlineExceeded

            if deadline_s is not None and time.monotonic() > deadline_s:
                # the escalation boundary: the probe's device time is
                # already paid, but the race leg has not started — an
                # expired request cancels it and answers 429
                raise DeadlineExceeded(
                    "deadline expired before the frontier race"
                )
            try:
                solution, info = self._frontier_solve(
                    arr, seed_states, deadline_s
                )
                if solution is None and info.get("capped"):
                    # same contract as the bucket path below: a race whose
                    # every subtree OVERFLOWed or was still RUNNING at
                    # max_iters has NOT proven the board unsolvable
                    # (ADVICE r4) — the HTTP surface still answers the
                    # reference's exact "No solution found" body, so the
                    # distinction is logged + carried in info["capped"]
                    logger.warning(
                        "solve_one: frontier race budget/stack exhausted — "
                        "board not finished, NOT proven unsolvable"
                    )
                return solution, info
            except DeadlineExceeded:
                # a shed request must stay shed: expiry mid-escalation is
                # the 429 path, never a bucket-path downgrade that would
                # serve (and bill) an answer nobody is waiting for
                raise
            except Exception:  # noqa: BLE001 — any race failure
                # A dead/failed frontier path (e.g. a failed collective
                # stopping the multi-host serving loop) must not take
                # /solve down with it: answer from the single-chip bucket
                # path and record the downgrade (surfaced at /metrics —
                # VERDICT r2 weak #3). The reference's analog failure is
                # its master busy-waiting forever on a lost cell
                # (reference node.py:554-555); we degrade, not hang.
                logger.exception(
                    "frontier path failed — serving this request from the "
                    "bucket path"
                )
                with self._lock:
                    self.frontier_fallbacks += 1
        return self._solve_one_bucket(arr)

    def _await_result(self, fut):
        """``fut.result()`` — BOUNDED when a supervisor is attached: a
        truly hung device call blocks the coalescer's completion thread
        forever, and an untimed wait would pin this handler thread (and
        with it a bounded-pool transport worker) just as permanently. The
        bound is past the watchdog's hang declaration by construction, so
        a trip has already rerouted serving when it fires; the starved
        future is cancelled (the completer's ``done()`` guard then skips
        it) and the raise sends THIS request to the fallback."""
        sup = self.supervisor
        if sup is None:
            return fut.result()
        timeout = 2.0 * sup.watchdog_budget_s + 5.0
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeout:
            fut.cancel()
            raise RuntimeError(
                f"supervised solve starved past {timeout:.1f}s "
                "(hung device call ahead of it?)"
            ) from None

    def _supervised_answer(self, sup, arr: np.ndarray, call, deadline_s=None):
        """THE degraded-serving contract, in one place (applied by
        ``_solve_one_bucket`` and ``solve_one_supervised``): an open
        breaker answers from the host-oracle fallback before the device
        is touched (the entry fallback honors ``deadline_s`` while
        queued on the fallback semaphore — queue wait only, like the
        coalescer); a device failure mid-call falls back instead of
        erroring the request (the seam already fed the breaker; service
        time was paid, so no deadline re-check); and every device answer
        is verified host-side so a poisoned program can never emit a
        silent wrong answer — a corrupted grid OR a false UNSAT claim.
        ``DeadlineExceeded`` always propagates — a shed request must
        stay shed."""
        from .serving.admission import DeadlineExceeded

        if sup.should_fallback():
            return sup.fallback_solve(arr, deadline_s=deadline_s)
        try:
            solution, info = call()
        except DeadlineExceeded:
            raise
        except Exception:
            logger.exception(
                "device path failed — answering from the host-oracle "
                "fallback"
            )
            return sup.fallback_solve(arr)
        tr = current_trace()
        if solution is not None:
            t_v = time.monotonic()
            ok = sup.check_solution(arr, solution)
            if tr is not None:
                # the host-side verification stage of this request's span
                tr.mark("verify", time.monotonic() - t_v)
            if not ok:
                # device call "succeeded" but the answer is wrong: the
                # poisoned-program failure mode — never serve it
                logger.error(
                    "device answer failed host-side verification — "
                    "poisoned program? answering from the fallback"
                )
                sup.record_failure(None, "bad-result")
                return sup.fallback_solve(arr)
        if solution is None and not info.get("capped"):
            # device claims PROVEN unsatisfiable (capped answers claim
            # only "not finished" and are exempt): cross-check — a
            # poisoned program clearing the solved flag is as wrong as
            # one corrupting the grid, and must trip the breaker too
            t_v = time.monotonic()
            alt, alt_info = sup.verify_unsat(arr)
            if tr is not None:
                tr.mark("verify", time.monotonic() - t_v)
            if alt is not None:
                sup.record_failure(None, "bad-result")
                return alt, alt_info
        return solution, info

    def _solve_one_bucket(self, arr: np.ndarray):
        """Single-board bucket path: coalesced with concurrent requests
        when enabled (parallel/coalescer.py), else a direct batch-1 call.
        With a supervisor attached this is the degraded-mode seam
        (``_supervised_answer``)."""
        sup = self.supervisor
        if sup is None:
            return self._solve_one_bucket_direct(arr)
        return self._supervised_answer(
            sup, arr, lambda: self._solve_one_bucket_direct(arr)
        )

    def _solve_one_bucket_direct(self, arr: np.ndarray):
        if self.coalesce:
            solution, info = self._await_result(self.coalescer.submit(arr))
        else:
            solutions, solved_mask, info = self.solve_batch_np(arr[None])
            solution = solutions[0].tolist() if solved_mask[0] else None
        if solution is None and info.get("capped"):
            # the HTTP surface must answer the reference's exact
            # "No solution found" body either way (http_api.py), so
            # the not-finished-vs-proven-UNSAT distinction lives here
            logger.warning(
                "solve_one: iteration budget exhausted (deep retry "
                "included) — board not finished, NOT proven unsolvable"
            )
        return solution, info

    def solve_one_async(
        self,
        board: Sequence[Sequence[int]],
        *,
        frontier: Optional[bool] = None,
        deadline_s: Optional[float] = None,
    ):
        """``solve_one`` returning a ``concurrent.futures.Future``.

        Bucket-path requests enqueue on the coalescer and return
        immediately — handler threads await the future instead of
        contending on a lock, and concurrent requests share one device
        call. Frontier-routed requests (and engines with ``coalesce=False``)
        bypass the coalescer and run inline in the calling thread: the race
        occupies the whole mesh by design and must not stall the bucket
        pipeline behind it.

        ``deadline_s`` (absolute ``time.monotonic()``, from the admission
        layer — serving/admission.py): a coalesced request still queued
        past it is dropped at batch formation and the future raises
        DeadlineExceeded; inline paths check it once before solving (work
        already started is never abandoned — the deadline guards queue
        wait, not service time).
        """
        from concurrent.futures import Future

        arr = np.asarray(board, np.int32)
        use_frontier = (
            self.frontier_enabled
            if frontier is None
            else (frontier and self.frontier_enabled)
        )
        if self.coalesce and not use_frontier:
            return self.coalescer.submit(arr, deadline_s)
        fut: Future = Future()
        try:
            if deadline_s is not None and time.monotonic() > deadline_s:
                from .serving.admission import DeadlineExceeded

                raise DeadlineExceeded(
                    "deadline expired before the solve started"
                )
            fut.set_result(
                self.solve_one(
                    board, frontier=frontier, deadline_s=deadline_s
                )
            )
        except BaseException as e:  # noqa: BLE001 — deliver through the future
            fut.set_exception(e)
        return fut

    def solve_one_supervised(
        self,
        board: Sequence[Sequence[int]],
        *,
        deadline_s: Optional[float] = None,
    ) -> Tuple[Optional[List[List[int]]], dict]:
        """``solve_one_async(...).result()`` with the supervisor's
        degraded-serving contract applied in the CALLING thread — the
        serving entry point ``net/node.py`` uses for /solve requests.

        Without a supervisor this is exactly the await the node used to
        do. With one, the ``_supervised_answer`` contract applies (open
        breaker → bounded host-oracle fallback; device failure OR a
        starved future — a hung batch ahead of this request — falls back
        instead of erroring or pinning the handler thread; answers are
        verified host-side). Deadline semantics are preserved:
        ``DeadlineExceeded`` always propagates (a shed request must stay
        shed — the 429 path), and the fallback honors an already-expired
        deadline the same way the inline path does. The fallback work
        runs HERE, in the handler's thread, never in the coalescer's
        completion thread. Inline routes (frontier engines,
        ``coalesce=False``) supervise inside ``_solve_one_bucket`` — one
        contract implementation, applied once per request."""
        sup = self.supervisor
        if sup is None:
            return self.solve_one_async(board, deadline_s=deadline_s).result()
        from .serving.admission import DeadlineExceeded

        arr = np.asarray(board, np.int32)
        if sup.should_fallback() and (
            deadline_s is not None and time.monotonic() > deadline_s
        ):
            raise DeadlineExceeded(
                "deadline expired before the solve started"
            )
        if self.coalesce and not self.frontier_enabled:
            return self._supervised_answer(
                sup,
                arr,
                lambda: self._await_result(
                    self.coalescer.submit(arr, deadline_s)
                ),
                deadline_s=deadline_s,
            )
        # inline paths run in this thread anyway; solve_one supervises
        # them in _solve_one_bucket (a failed frontier race already
        # downgrades there) — wrapping again here would just re-verify
        if deadline_s is not None and time.monotonic() > deadline_s:
            raise DeadlineExceeded(
                "deadline expired before the solve started"
            )
        return self.solve_one(board, deadline_s=deadline_s)
