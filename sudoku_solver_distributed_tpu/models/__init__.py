"""Host-side model components: trusted oracle solver, puzzle generator, corpora."""

from .oracle import oracle_solve, oracle_is_valid_solution, count_solutions
from .generator import generate_board, generate_batch

__all__ = [
    "oracle_solve",
    "oracle_is_valid_solution",
    "count_solutions",
    "generate_board",
    "generate_batch",
]
