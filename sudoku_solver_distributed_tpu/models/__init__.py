"""Host-side model components: trusted oracle solver, puzzle generator, corpora."""

from .oracle import (
    OracleBudgetExceeded,
    count_solutions,
    oracle_is_valid_solution,
    oracle_solve,
)
from .generator import generate_board, generate_batch

__all__ = [
    "OracleBudgetExceeded",
    "oracle_solve",
    "oracle_is_valid_solution",
    "count_solutions",
    "generate_board",
    "generate_batch",
]
