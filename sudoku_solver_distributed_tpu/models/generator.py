"""Puzzle generation — the test-input fabric (reference gen.py:6-66 equivalent).

Same recipe as the reference generator: fill the n independent diagonal boxes
with random permutations, complete the board with a real backtracker, then
blank a requested number of distinct cells (reference gen.py:31-52). Extended
beyond the reference with: arbitrary board sizes, seeded determinism, batch
generation, and an optional unique-solution certificate (the reference can
emit multi-solution puzzles, which makes golden testing flaky).
"""

from __future__ import annotations

import random
from typing import Optional

import numpy as np

from .oracle import Board, count_solutions, oracle_solve
from .. import native


def _solve(board: Board) -> Optional[Board]:
    """Native C++ oracle when available (bit-identical results), else Python."""
    if native.available():
        return native.native_solve(board)
    return oracle_solve(board)


# Uniqueness-probe node budget: bounds the pathological tail (a single
# near-multi-solution probe on a 16×16 can otherwise take minutes). An
# inconclusive probe reads as "not proven unique", so the blank is reverted —
# certification stays sound, the puzzle just keeps one more clue.
_COUNT_NODE_BUDGET = 30_000


def _count(board: Board, limit: int) -> int:
    if native.available():
        rc = native.native_count_solutions_budget(
            board, limit=limit, max_nodes=_COUNT_NODE_BUDGET
        )
        return limit if rc is None else rc
    return count_solutions(board, limit=limit)


def generate_board(
    empty_boxes: int = 0,
    *,
    size: int = 9,
    rng: Optional[random.Random] = None,
    unique: bool = False,
) -> Board:
    """Generate one puzzle with ``empty_boxes`` blanked cells.

    With ``unique=True`` cells are only blanked while the puzzle keeps a
    single solution (so ``empty_boxes`` becomes an upper bound).
    """
    rng = rng or random.Random()
    box = int(round(size ** 0.5))
    board = [[0] * size for _ in range(size)]

    # Diagonal boxes are mutually independent: fill each with a permutation.
    for n in range(0, size, box):
        nums = list(range(1, size + 1))
        rng.shuffle(nums)
        for i in range(box):
            for j in range(box):
                board[n + i][n + j] = nums.pop()

    solved = None
    if size > 9:
        # Completing a near-empty large board with the deterministic MRV
        # solver has a pathological tail (minutes on some 16×16 diagonal
        # seeds); the randomized-restart native solver finishes in
        # milliseconds and stays deterministic in the rng stream. 9×9 keeps
        # the historical deterministic path so existing seeded corpora
        # reproduce bit-for-bit. The seed is drawn unconditionally so the
        # rng stream (and thus the blanking order below) is identical with
        # or without the native toolchain.
        solver_seed = rng.getrandbits(64)
        if native.available():
            try:
                solved = native.native_solve_seeded(board, solver_seed)
            except RuntimeError:
                solved = None  # all restarts exhausted: exhaustive fallback
    if solved is None:
        solved = _solve(board)
    assert solved is not None, "diagonal seed must always be completable"
    board = solved

    filled = [(i, j) for i in range(size) for j in range(size)]
    rng.shuffle(filled)
    removed = 0
    for i, j in filled:
        if removed >= empty_boxes:
            break
        keep = board[i][j]
        board[i][j] = 0
        if unique and _count(board, limit=2) != 1:
            board[i][j] = keep
            continue
        removed += 1
    return board


def generate_batch(
    batch: int,
    empty_boxes: int,
    *,
    size: int = 9,
    seed: int = 0,
    unique: bool = False,
) -> np.ndarray:
    """(batch, size, size) int32 array of puzzles, deterministic in ``seed``."""
    rng = random.Random(seed)
    out = np.empty((batch, size, size), dtype=np.int32)
    for k in range(batch):
        out[k] = generate_board(empty_boxes, size=size, rng=rng, unique=unique)
    return out
