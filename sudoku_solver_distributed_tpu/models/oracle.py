"""Trusted host-side (pure Python) sudoku solver — the test oracle.

The reference has no tests at all (SURVEY.md §4); its only complete solver is
a naive recursive backtracker that is dead code (reference node.py:62-74).
This oracle exists so the TPU kernels can be property-tested against an
independent implementation: a bitmask MRV backtracker over plain Python ints.
It is intentionally written in a different style from both the reference and
the device kernels (recursive, dict-free, host ints) so that agreement between
oracle and kernel is meaningful evidence of correctness.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional, Sequence

Board = List[List[int]]

# deadline-check cadence for budgeted solves: one time.monotonic() per
# this many MRV steps keeps the check under ~1 ns amortized per step while
# still bounding overrun to a few hundred microseconds of host work
_BUDGET_CHECK_EVERY = 128


class OracleBudgetExceeded(Exception):
    """A budgeted ``oracle_solve`` ran past its wall-time budget.

    The host MRV backtracker's worst case is exponential (adversarial
    16×16/25×25 refutations), and its serving-path callers — the
    supervisor's degraded-mode fallback (serving/health.py) — must answer
    a clean 503 instead of pinning a host core for minutes (PR 5 known
    limit, closed in ISSUE 8). Deliberately NOT a subclass of ValueError
    or RuntimeError: a budget trip means "undetermined", never "invalid
    board" or "no solution", and callers must not conflate them.
    """


def _geometry(board: Sequence[Sequence[int]]):
    size = len(board)
    box = math.isqrt(size)
    if box * box != size or any(len(r) != size for r in board):
        raise ValueError("board must be N×N with N a perfect square")
    return size, box


def oracle_is_valid_solution(board: Sequence[Sequence[int]]) -> bool:
    """Strict check: every row/col/box is a permutation of 1..N."""
    size, box = _geometry(board)
    want = set(range(1, size + 1))
    for i in range(size):
        if set(board[i]) != want:
            return False
        if {board[r][i] for r in range(size)} != want:
            return False
    for bi in range(0, size, box):
        for bj in range(0, size, box):
            vals = {
                board[bi + di][bj + dj] for di in range(box) for dj in range(box)
            }
            if vals != want:
                return False
    return True


def _masks(board: Sequence[Sequence[int]], size: int, box: int):
    rows = [0] * size
    cols = [0] * size
    boxes = [0] * size
    for i in range(size):
        for j in range(size):
            v = board[i][j]
            if v:
                if v < 0 or v > size:
                    return None  # out-of-range clue: unsatisfiable as given
                bit = 1 << (v - 1)
                b = (i // box) * box + (j // box)
                if rows[i] & bit or cols[j] & bit or boxes[b] & bit:
                    return None  # clue conflict: unsatisfiable as given
                rows[i] |= bit
                cols[j] |= bit
                boxes[b] |= bit
    return rows, cols, boxes


def oracle_solve(
    board: Sequence[Sequence[int]], budget_s: Optional[float] = None
) -> Optional[Board]:
    """Return a solved copy, or None if unsatisfiable. MRV backtracking.

    ``budget_s`` bounds wall time: past it the search raises
    :class:`OracleBudgetExceeded` (checked every ``_BUDGET_CHECK_EVERY``
    MRV steps — amortized free, bounded overrun). None (default): the old
    unbudgeted contract, unchanged for every test-oracle caller."""
    size, box = _geometry(board)
    deadline = None
    if budget_s is not None:
        if budget_s <= 0:
            raise OracleBudgetExceeded(
                f"oracle budget {budget_s}s already spent"
            )
        deadline = time.monotonic() + budget_s
    steps = 0
    grid = [list(r) for r in board]
    m = _masks(grid, size, box)
    if m is None:
        return None
    rows, cols, boxes = m
    full = (1 << size) - 1
    empties = [(i, j) for i in range(size) for j in range(size) if not grid[i][j]]

    def step() -> bool:
        nonlocal steps
        if deadline is not None:
            steps += 1
            # first check at step 1 (an already-blown budget trips before
            # any work — deterministic for callers and tests), then every
            # _BUDGET_CHECK_EVERY steps (amortized free)
            if steps % _BUDGET_CHECK_EVERY in (0, 1) and (
                time.monotonic() > deadline
            ):
                raise OracleBudgetExceeded(
                    f"oracle budget {budget_s}s exceeded after "
                    f"{steps} MRV steps"
                )
        best = -1
        best_cand = 0
        best_n = size + 1
        for k, (i, j) in enumerate(empties):
            if grid[i][j]:
                continue
            b = (i // box) * box + (j // box)
            cand = full & ~(rows[i] | cols[j] | boxes[b])
            n = cand.bit_count()
            if n == 0:
                return False
            if n < best_n:
                best, best_cand, best_n = k, cand, n
                if n == 1:
                    break
        if best < 0:
            return True
        i, j = empties[best]
        b = (i // box) * box + (j // box)
        cand = best_cand
        while cand:
            bit = cand & -cand
            cand &= ~bit
            grid[i][j] = bit.bit_length()
            rows[i] |= bit
            cols[j] |= bit
            boxes[b] |= bit
            if step():
                return True
            grid[i][j] = 0
            rows[i] &= ~bit
            cols[j] &= ~bit
            boxes[b] &= ~bit
        return False

    return grid if step() else None


def count_solutions(board: Sequence[Sequence[int]], limit: int = 2) -> int:
    """Count solutions up to ``limit`` (used to certify unique-solution puzzles)."""
    size, box = _geometry(board)
    if limit <= 0:
        return 0
    grid = [list(r) for r in board]
    m = _masks(grid, size, box)
    if m is None:
        return 0
    rows, cols, boxes = m
    full = (1 << size) - 1
    found = 0

    def step() -> bool:  # returns True when the limit is reached
        nonlocal found
        best = None
        best_cand = 0
        best_n = size + 1
        for i in range(size):
            for j in range(size):
                if grid[i][j]:
                    continue
                b = (i // box) * box + (j // box)
                cand = full & ~(rows[i] | cols[j] | boxes[b])
                n = cand.bit_count()
                if n == 0:
                    return False
                if n < best_n:
                    best, best_cand, best_n = (i, j), cand, n
        if best is None:
            found += 1
            return found >= limit
        i, j = best
        b = (i // box) * box + (j // box)
        cand = best_cand
        while cand:
            bit = cand & -cand
            cand &= ~bit
            grid[i][j] = bit.bit_length()
            rows[i] |= bit
            cols[j] |= bit
            boxes[b] |= bit
            done = step()
            grid[i][j] = 0
            rows[i] &= ~bit
            cols[j] &= ~bit
            boxes[b] &= ~bit
            if done:
                return True
        return False

    step()
    return found
