"""Native (C++) host runtime components, loaded via ctypes.

The compute path of this framework is JAX/XLA on TPU; the *host* runtime
around it — here, the oracle solver / solution counter that certifies
unique-solution puzzles during corpus generation (models/generator.py) — is
native C++ for speed. The reference is pure Python with no native code
(SURVEY.md §2), so this is an extension, not a parity obligation; everything
degrades gracefully to the pure-Python oracle when no C++ toolchain exists.

Build model: ``oracle.cc`` is compiled on first use with whatever C++
compiler is on PATH (g++/clang++/cc) into ``_build/liboracle-<hash>.so``
keyed by a source hash, so edits rebuild automatically and the build is a
no-op afterwards. No pybind11 / setuptools involvement — the ABI is five
plain C functions bound with ctypes.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import shutil
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "oracle.cc"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _compiler() -> Optional[str]:
    for cc in ("g++", "clang++", "c++"):
        path = shutil.which(cc)
        if path:
            return path
    return None


def _build() -> Optional[ctypes.CDLL]:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _HERE / "_build" / f"liboracle-{tag}.so"
    if not out.exists():
        cc = _compiler()
        if cc is None:
            logger.info("no C++ compiler on PATH; native oracle disabled")
            return None
        out.parent.mkdir(exist_ok=True)
        tmp = out.with_suffix(f".tmp{os.getpid()}")
        cmd = [cc, "-O2", "-shared", "-fPIC", "-o", str(tmp), str(_SRC)]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True, timeout=120
            )
        except (subprocess.SubprocessError, OSError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning("native oracle build failed: %s", detail)
            return None
        os.replace(tmp, out)  # atomic: concurrent builders race harmlessly
    try:
        lib = ctypes.CDLL(str(out))
    except OSError as e:
        # e.g. a cached .so built on another platform (the cache key is
        # source-only); degrade to the Python oracle rather than crash.
        logger.warning("native oracle load failed: %s", e)
        return None
    lib.ss_solve.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
    ]
    lib.ss_solve.restype = ctypes.c_int
    lib.ss_count.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.c_longlong,
    ]
    lib.ss_count.restype = ctypes.c_longlong
    lib.ss_count_budget.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.c_longlong,
        ctypes.c_longlong,
    ]
    lib.ss_count_budget.restype = ctypes.c_longlong
    lib.ss_solve_seeded.argtypes = [
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
        ctypes.c_uint64,
        ctypes.c_longlong,
        ctypes.c_int,
    ]
    lib.ss_solve_seeded.restype = ctypes.c_int
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is None and not _lib_failed:
            _lib = _build()
            _lib_failed = _lib is None
    return _lib


def available() -> bool:
    """True iff the native library is (or can be) loaded."""
    return _get_lib() is not None


def _as_c_board(board: Sequence[Sequence[int]]) -> tuple:
    arr = np.ascontiguousarray(board, dtype=np.int32)
    if arr.ndim != 2 or arr.shape[0] != arr.shape[1]:
        raise ValueError("board must be square")
    return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def native_solve(board: Sequence[Sequence[int]]) -> Optional[List[List[int]]]:
    """Solved copy of ``board`` or None if unsatisfiable.

    Bit-for-bit the same result as models.oracle.oracle_solve (same MRV
    tie-breaking, same candidate order); raises RuntimeError if the native
    library is unavailable — callers decide their own fallback.
    """
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native oracle unavailable")
    arr, ptr = _as_c_board(board)
    size = arr.shape[0]
    out = np.zeros_like(arr)
    rc = lib.ss_solve(ptr, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), size)
    if rc < 0:
        raise ValueError(f"bad board geometry: {size}×{size}")
    return out.tolist() if rc == 1 else None


def native_count_solutions(board: Sequence[Sequence[int]], limit: int = 2) -> int:
    """Number of solutions of ``board``, saturated at ``limit``."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native oracle unavailable")
    arr, ptr = _as_c_board(board)
    rc = lib.ss_count(ptr, arr.shape[0], limit)
    if rc < 0:
        raise ValueError(f"bad board geometry: {arr.shape[0]}×{arr.shape[0]}")
    return int(rc)


def native_count_solutions_budget(
    board: Sequence[Sequence[int]], limit: int = 2, max_nodes: int = 0
) -> Optional[int]:
    """As ``native_count_solutions`` but bounded to ``max_nodes`` search
    nodes (0 = unbounded). Returns None when the budget ran out before the
    count settled — "unknown", which certification callers must treat
    conservatively (uniqueness probes on large boards have a pathological
    tail: a near-multi-solution 16×16 can take minutes unbounded)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native oracle unavailable")
    arr, ptr = _as_c_board(board)
    rc = lib.ss_count_budget(ptr, arr.shape[0], limit, max_nodes)
    if rc == -2:
        return None
    if rc < 0:
        raise ValueError(f"bad board geometry: {arr.shape[0]}×{arr.shape[0]}")
    return int(rc)


def native_solve_seeded(
    board: Sequence[Sequence[int]],
    seed: int,
    *,
    max_nodes: int = 200_000,
    restarts: int = 32,
) -> Optional[List[List[int]]]:
    """Randomized-restart solve (Las Vegas): candidate values in a
    seeded-shuffled order, restarting on node-budget exhaustion.

    Deterministic in ``seed``. Use for *generation-style* inputs that are
    known satisfiable — deterministic MRV ordering has pathological tails on
    large near-empty boards (minutes on some 16×16 diagonal seeds) that
    shuffled restarts dodge with overwhelming probability. Returns None if
    unsatisfiable; raises RuntimeError if every restart exhausted its budget
    (adversarial input — fall back to the exhaustive solver)."""
    lib = _get_lib()
    if lib is None:
        raise RuntimeError("native oracle unavailable")
    arr, ptr = _as_c_board(board)
    size = arr.shape[0]
    out = np.zeros_like(arr)
    rc = lib.ss_solve_seeded(
        ptr,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        size,
        seed & (2**64 - 1),
        max_nodes,
        restarts,
    )
    if rc == -1:
        raise ValueError(f"bad board geometry: {size}×{size}")
    if rc == -2:
        raise RuntimeError("seeded solve: all restarts exhausted their budget")
    return out.tolist() if rc == 1 else None


__all__ = [
    "available",
    "native_solve",
    "native_count_solutions",
    "native_count_solutions_budget",
    "native_solve_seeded",
]
