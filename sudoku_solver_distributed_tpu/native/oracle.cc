// Native host oracle: bitmask MRV backtracking solver / solution counter.
//
// C++ twin of the pure-Python oracle (models/oracle.py) with byte-identical
// deterministic behavior: cells are chosen by a row-major scan taking the
// first strictly-smaller candidate count (early exit at 1), and candidate
// values are tried lowest-set-bit first. Because the tie-breaking matches,
// `ss_solve` returns the exact same solution grid as `oracle_solve`, which
// lets the test suite assert native ≡ Python ≡ TPU-kernel agreement.
//
// The reference has no native code at all (SURVEY.md §2); this exists because
// the framework's corpus generator certifies unique-solution puzzles with a
// solution-count probe per blanked cell (models/generator.py), and that host
// loop is worth real native speed (~100× over CPython on 9×9 counting).
//
// Board sizes: N×N for N in {4, 9, 16, 25} (box edge 2..5). Candidate sets are
// uint32 bitmasks; values are 1..N, 0 = empty.

#include <cstdint>

namespace {

constexpr int kMaxN = 25;

struct Ctx {
  int size = 0;
  int box = 0;
  uint32_t full = 0;
  uint32_t rows[kMaxN];
  uint32_t cols[kMaxN];
  uint32_t boxes[kMaxN];
  int32_t grid[kMaxN][kMaxN];
  long long found = 0;
  long long limit = 0;
};

inline int box_of(const Ctx& c, int i, int j) {
  return (i / c.box) * c.box + (j / c.box);
}

// Load a board into ctx; returns false on a direct clue conflict (duplicate
// value in a unit) or an out-of-range value — unsatisfiable as given.
bool load(Ctx& c, const int32_t* board, int size, int box) {
  c.size = size;
  c.box = box;
  c.full = (size == 32) ? 0xffffffffu : ((1u << size) - 1u);
  for (int u = 0; u < size; ++u) c.rows[u] = c.cols[u] = c.boxes[u] = 0;
  for (int i = 0; i < size; ++i) {
    for (int j = 0; j < size; ++j) {
      int32_t v = board[i * size + j];
      c.grid[i][j] = v;
      if (v == 0) continue;
      if (v < 0 || v > size) return false;
      uint32_t bit = 1u << (v - 1);
      int b = box_of(c, i, j);
      if ((c.rows[i] & bit) || (c.cols[j] & bit) || (c.boxes[b] & bit))
        return false;
      c.rows[i] |= bit;
      c.cols[j] |= bit;
      c.boxes[b] |= bit;
    }
  }
  return true;
}

// MRV backtracking step. Returns true when the search should stop (for
// solving: a solution was found; for counting: the limit was reached).
bool step(Ctx& c) {
  int bi = -1, bj = -1, bn = c.size + 1;
  uint32_t bcand = 0;
  for (int i = 0; i < c.size && bn > 1; ++i) {
    for (int j = 0; j < c.size; ++j) {
      if (c.grid[i][j]) continue;
      uint32_t cand =
          c.full & ~(c.rows[i] | c.cols[j] | c.boxes[box_of(c, i, j)]);
      int n = __builtin_popcount(cand);
      if (n == 0) return false;
      if (n < bn) {
        bi = i;
        bj = j;
        bn = n;
        bcand = cand;
        if (n == 1) break;
      }
    }
  }
  if (bi < 0) {  // complete
    ++c.found;
    return c.found >= c.limit;
  }
  int b = box_of(c, bi, bj);
  uint32_t cand = bcand;
  while (cand) {
    uint32_t bit = cand & (~cand + 1u);
    cand &= ~bit;
    c.grid[bi][bj] = __builtin_ctz(bit) + 1;
    c.rows[bi] |= bit;
    c.cols[bj] |= bit;
    c.boxes[b] |= bit;
    bool done = step(c);
    if (done && c.limit == 1) return true;  // solving: keep the filled grid
    c.grid[bi][bj] = 0;
    c.rows[bi] &= ~bit;
    c.cols[bj] &= ~bit;
    c.boxes[b] &= ~bit;
    if (done) return true;
  }
  return false;
}

int geometry_box(int size) {
  for (int b = 2; b <= 5; ++b)
    if (b * b == size) return b;
  return -1;
}

}  // namespace

extern "C" {

// Solve `board` (size*size int32, row-major). On success writes the solved
// grid to `out` and returns 1; returns 0 if unsatisfiable, -1 on bad geometry.
int ss_solve(const int32_t* board, int32_t* out, int size) {
  int box = geometry_box(size);
  if (box < 0) return -1;
  static thread_local Ctx c;
  if (!load(c, board, size, box)) return 0;
  c.found = 0;
  c.limit = 1;
  if (!step(c)) return 0;
  for (int i = 0; i < size; ++i)
    for (int j = 0; j < size; ++j) out[i * size + j] = c.grid[i][j];
  return 1;
}

// Count solutions of `board`, stopping at `limit`. Returns the count
// (saturated at limit), or -1 on bad geometry.
long long ss_count(const int32_t* board, int size, long long limit) {
  int box = geometry_box(size);
  if (box < 0) return -1;
  if (limit <= 0) return 0;
  static thread_local Ctx c;
  if (!load(c, board, size, box)) return 0;
  c.found = 0;
  c.limit = limit;
  step(c);
  return c.found;
}

}  // extern "C"
