// Native host oracle: bitmask MRV backtracking solver / solution counter.
//
// C++ twin of the pure-Python oracle (models/oracle.py) with byte-identical
// deterministic behavior: cells are chosen by a row-major scan taking the
// first strictly-smaller candidate count (early exit at 1), and candidate
// values are tried lowest-set-bit first. Because the tie-breaking matches,
// `ss_solve` returns the exact same solution grid as `oracle_solve`, which
// lets the test suite assert native ≡ Python ≡ TPU-kernel agreement.
//
// The reference has no native code at all (SURVEY.md §2); this exists because
// the framework's corpus generator certifies unique-solution puzzles with a
// solution-count probe per blanked cell (models/generator.py), and that host
// loop is worth real native speed (~100× over CPython on 9×9 counting).
//
// Board sizes: N×N for N in {4, 9, 16, 25} (box edge 2..5). Candidate sets are
// uint32 bitmasks; values are 1..N, 0 = empty.

#include <cstdint>

namespace {

constexpr int kMaxN = 25;

struct Ctx {
  int size = 0;
  int box = 0;
  uint32_t full = 0;
  uint32_t rows[kMaxN];
  uint32_t cols[kMaxN];
  uint32_t boxes[kMaxN];
  int32_t grid[kMaxN][kMaxN];
  long long found = 0;
  long long limit = 0;
  long long nodes = 0;       // search nodes expanded so far
  long long max_nodes = 0;   // 0 = unbounded
  bool budget_hit = false;
  uint64_t rng = 0;          // 0 = deterministic lowest-bit-first ordering
};

inline uint64_t next_rng(uint64_t& s) {  // xorshift64*
  s ^= s >> 12;
  s ^= s << 25;
  s ^= s >> 27;
  return s * 0x2545F4914F6CDD1DULL;
}

inline int box_of(const Ctx& c, int i, int j) {
  return (i / c.box) * c.box + (j / c.box);
}

// Load a board into ctx; returns false on a direct clue conflict (duplicate
// value in a unit) or an out-of-range value — unsatisfiable as given.
bool load(Ctx& c, const int32_t* board, int size, int box) {
  c.size = size;
  c.box = box;
  c.full = (size == 32) ? 0xffffffffu : ((1u << size) - 1u);
  for (int u = 0; u < size; ++u) c.rows[u] = c.cols[u] = c.boxes[u] = 0;
  for (int i = 0; i < size; ++i) {
    for (int j = 0; j < size; ++j) {
      int32_t v = board[i * size + j];
      c.grid[i][j] = v;
      if (v == 0) continue;
      if (v < 0 || v > size) return false;
      uint32_t bit = 1u << (v - 1);
      int b = box_of(c, i, j);
      if ((c.rows[i] & bit) || (c.cols[j] & bit) || (c.boxes[b] & bit))
        return false;
      c.rows[i] |= bit;
      c.cols[j] |= bit;
      c.boxes[b] |= bit;
    }
  }
  return true;
}

// MRV backtracking step. Returns true when the search should stop (for
// solving: a solution was found; for counting: the limit was reached; for
// either: the node budget was exhausted).
bool step(Ctx& c) {
  if (c.max_nodes && ++c.nodes > c.max_nodes) {
    c.budget_hit = true;
    return true;
  }
  int bi = -1, bj = -1, bn = c.size + 1;
  uint32_t bcand = 0;
  for (int i = 0; i < c.size && bn > 1; ++i) {
    for (int j = 0; j < c.size; ++j) {
      if (c.grid[i][j]) continue;
      uint32_t cand =
          c.full & ~(c.rows[i] | c.cols[j] | c.boxes[box_of(c, i, j)]);
      int n = __builtin_popcount(cand);
      if (n == 0) return false;
      if (n < bn) {
        bi = i;
        bj = j;
        bn = n;
        bcand = cand;
        if (n == 1) break;
      }
    }
  }
  if (bi < 0) {  // complete
    ++c.found;
    return c.found >= c.limit;
  }
  int b = box_of(c, bi, bj);
  // candidate order: deterministic lowest-bit-first (the Python-oracle
  // contract), or Fisher-Yates shuffled when an rng stream is active
  // (randomized-restart solving for generation; see ss_solve_seeded)
  uint32_t order[kMaxN];
  int ncand = 0;
  uint32_t cand = bcand;
  while (cand) {
    uint32_t bit = cand & (~cand + 1u);
    cand &= ~bit;
    order[ncand++] = bit;
  }
  if (c.rng) {
    for (int i = ncand - 1; i > 0; --i) {
      int j = static_cast<int>(next_rng(c.rng) % (i + 1));
      uint32_t t = order[i];
      order[i] = order[j];
      order[j] = t;
    }
  }
  for (int k = 0; k < ncand; ++k) {
    uint32_t bit = order[k];
    c.grid[bi][bj] = __builtin_ctz(bit) + 1;
    c.rows[bi] |= bit;
    c.cols[bj] |= bit;
    c.boxes[b] |= bit;
    bool done = step(c);
    if (done && c.limit == 1) return true;  // solving: keep the filled grid
    c.grid[bi][bj] = 0;
    c.rows[bi] &= ~bit;
    c.cols[bj] &= ~bit;
    c.boxes[b] &= ~bit;
    if (done) return true;
  }
  return false;
}

int geometry_box(int size) {
  for (int b = 2; b <= 5; ++b)
    if (b * b == size) return b;
  return -1;
}

}  // namespace

extern "C" {

// Solve `board` (size*size int32, row-major). On success writes the solved
// grid to `out` and returns 1; returns 0 if unsatisfiable, -1 on bad geometry.
int ss_solve(const int32_t* board, int32_t* out, int size) {
  int box = geometry_box(size);
  if (box < 0) return -1;
  static thread_local Ctx c;
  if (!load(c, board, size, box)) return 0;
  c.found = 0;
  c.limit = 1;
  c.nodes = 0;
  c.max_nodes = 0;
  c.budget_hit = false;
  c.rng = 0;
  if (!step(c)) return 0;
  for (int i = 0; i < size; ++i)
    for (int j = 0; j < size; ++j) out[i * size + j] = c.grid[i][j];
  return 1;
}

// Count solutions of `board`, stopping at `limit`. Returns the count
// (saturated at limit), or -1 on bad geometry.
long long ss_count(const int32_t* board, int size, long long limit) {
  int box = geometry_box(size);
  if (box < 0) return -1;
  if (limit <= 0) return 0;
  static thread_local Ctx c;
  if (!load(c, board, size, box)) return 0;
  c.found = 0;
  c.limit = limit;
  c.nodes = 0;
  c.max_nodes = 0;
  c.budget_hit = false;
  c.rng = 0;
  step(c);
  return c.found;
}

// As ss_count, but give up after expanding `max_nodes` search nodes
// (0 = unbounded). Returns -2 when the budget was exhausted before the
// count was settled — callers must treat that as "unknown", not a count.
// Bounds the pathological tail of uniqueness probes on large boards (a
// near-multi-solution 16x16 can take minutes unbounded).
long long ss_count_budget(const int32_t* board, int size, long long limit,
                          long long max_nodes) {
  int box = geometry_box(size);
  if (box < 0) return -1;
  if (limit <= 0) return 0;
  static thread_local Ctx c;
  if (!load(c, board, size, box)) return 0;
  c.found = 0;
  c.limit = limit;
  c.nodes = 0;
  c.max_nodes = max_nodes;
  c.budget_hit = false;
  c.rng = 0;
  step(c);
  if (c.budget_hit && c.found < limit) return -2;
  return c.found;
}

// Randomized-restart solve: candidate values tried in a seeded-shuffled
// order, restarting with a fresh stream whenever `max_nodes` search nodes
// are exhausted (Las Vegas — deterministic MRV orderings have pathological
// tails on large near-empty boards, e.g. minutes on some 16x16 diagonal
// seeds; shuffled restarts finish in milliseconds with overwhelming
// probability). Returns 1 + fills `out` on success, 0 if proven
// unsatisfiable, -1 on bad geometry, -2 if every restart exhausted its
// budget (UNKNOWN — only possible on unsatisfiable-or-adversarial inputs;
// callers fall back or reseed).
int ss_solve_seeded(const int32_t* board, int32_t* out, int size,
                    uint64_t seed, long long max_nodes, int restarts) {
  int box = geometry_box(size);
  if (box < 0) return -1;
  static thread_local Ctx c;
  if (max_nodes <= 0) max_nodes = 200000;
  if (restarts <= 0) restarts = 32;
  for (int attempt = 0; attempt < restarts; ++attempt) {
    if (!load(c, board, size, box)) return 0;
    c.found = 0;
    c.limit = 1;
    c.nodes = 0;
    c.max_nodes = max_nodes;
    c.budget_hit = false;
    c.rng = seed + 0x9E3779B97F4A7C15ULL * (attempt + 1);
    if (c.rng == 0) c.rng = 1;
    bool done = step(c);
    if (done && !c.budget_hit) {
      for (int i = 0; i < size; ++i)
        for (int j = 0; j < size; ++j) out[i * size + j] = c.grid[i][j];
      return 1;
    }
    if (!done && !c.budget_hit) return 0;  // full search: unsatisfiable
  }
  return -2;
}

}  // extern "C"
