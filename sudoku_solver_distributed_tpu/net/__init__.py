"""P2P service layer: wire protocol, membership, stats gossip, node, HTTP API.

The host-side control plane of the framework. The wire surface — 7 UDP JSON
message types (reference README.md:69-79) and 3 HTTP routes (reference
node.py:666-704) — is byte-identical to the reference; the compute behind it
is the TPU engine (engine.py / parallel/). Known reference defects are fixed
behind the same surface: proper locking instead of the free-running
cross-thread mutation (SURVEY.md §5), task timeouts instead of the
incomplete-board early-exit (reference node.py:462-464), a threaded HTTP
server instead of /stats blocking behind /solve, and a configurable bind host
instead of the hardcoded LAN IP (reference node.py:708, 726).
"""

from .wire import Msg, encode_msg, decode_msg, parse_address
from .stats import StatsGossip
from .membership import Membership
from .node import P2PNode
from .http_api import make_http_server
from .solver_api import SudokuSolver

__all__ = [
    "Msg",
    "encode_msg",
    "decode_msg",
    "parse_address",
    "StatsGossip",
    "Membership",
    "P2PNode",
    "SudokuSolver",
    "make_http_server",
]
