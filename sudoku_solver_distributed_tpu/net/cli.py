"""Node CLI — flag-compatible with the reference (reference node.py:715-730).

Same four flags with the same meanings and defaults:
  -p  HTTP port (default 8001)
  -s  P2P/UDP port (default 7000)
  -a  anchor node "host:port"
  -h  handicap in ms, divided by 100 into base_delay seconds (the reference's
      conversion, node.py:726); argparse uses conflict_handler='resolve' so
      -h means handicap, not help, exactly as the reference does.

Extensions (defaults preserve reference behavior):
  --host        bind address (default 127.0.0.1 — the reference hardcodes its
                authors' LAN IP 192.168.1.126, node.py:708/726, and cannot
                start anywhere else [SURVEY.md §2 verified live]; a
                configurable host is the defect fix)
  --mesh-peers  N: surface N TPU-core pseudo-peers at /network (the
                north-star mapping, BASELINE.json); default 0
  --no-warmup   skip engine pre-compilation (faster start, slower first solve)
  --metrics     expose GET /metrics (per-route latency percentiles); off by
                default so the unknown-path 404 surface stays byte-identical
  --batch-api   expose POST /solve_batch — many boards per request through
                the engine's bucketed batch path (the bench.py throughput
                strength on the serving surface); off by default, same
                404-parity reason
  --serving-stats
                add a "serving" block (request-coalescer batch-fill, queue
                depth, wait times) to GET /stats; off by default so the
                reference's {"all","nodes"} body stays byte-identical
  --no-coalesce / --coalesce-max-wait-ms / --coalesce-max-batch
                disable or tune the request-coalescing micro-batch
                scheduler (parallel/coalescer.py) that merges concurrent
                /solve requests into one bucketed device call; max-batch
                caps boards per call at the backend's efficient width
                (8 on the CPU fallback — engine.py rationale)
  --no-continuous / --segment-iters
                continuous batching (PR 12, default ON): the coalesced
                path runs bounded k-iteration device segments over a lane
                pool, resolving finished lanes and injecting fresh boards
                mid-flight; --no-continuous restores the closed-loop
                dispatcher (A/B arm), --segment-iters sweeps k
  --no-segment-pipeline
                disable the pipelined segment boundary (PR 15, default
                ON with continuous): digest-only boundary fetch, state
                buffer donation, and overlapped host refill fall back
                to the PR 12 full-row boundary byte-for-byte (A/B arm)
  --deep-lane-cap
                with continuous batching: bound the lanes boards resident
                longer than a few segments may occupy while demand
                queues; overage evicts to the deep-retry net (fairness
                slice, ISSUE 13). 0 (default) = no cap
  --no-answer-cache / --answer-cache-capacity / --cache-fetch-timeout-ms
                canonical-form answer cache (cache/, ISSUE 13; ON by
                default): /solve and /solve_batch boards canonicalize
                over the sudoku symmetry group at the front door and
                repeats — or symmetries — of already-verified answers
                serve from an LRU in microseconds (X-Cache: hit) without
                touching admission or the device; the hot-set digest
                gossips on the stats heartbeat and local misses on
                peer-advertised keys fetch the answer over UDP (verified
                on arrival). --no-answer-cache is the A/B escape hatch
  --profile-dir write a jax.profiler device trace of each /solve to this dir
  --failure-timeout
                seconds of neighbor silence before a crash is declared (the
                gossip heartbeat); 0 restores the reference's graceful-only
                failure model
  --admission-capacity / --default-deadline-ms
                overload control plane (serving/admission.py): bounded
                pending budget and per-request deadlines (X-Deadline-Ms
                header); overload answers 429 + Retry-After instead of
                queueing without bound, and expired requests are dropped
                before the device runs them. Both default off
  --adaptive-coalesce
                scale the coalescer's wait budgets with the measured
                arrival rate (near-zero when idle, the configured caps
                under load — serving/load.py)
  --http-workers
                bounded connection-worker pool for the serving transport
                (net/fastserve.py; default 128)
  --coordinator / --num-hosts / --host-id
                multi-host mode: initialize jax.distributed against the
                coordinator ("host:port") so the engine's mesh spans a pod
                slice; the P2P/HTTP control plane is unchanged (SURVEY.md §5
                distributed-backend row)
  --no-mesh     disable mesh-parallel bucket serving (ISSUE 8). DEFAULT ON
                with >1 device: every bucket program is a shard_map over
                the data axis, so one coalesced micro-batch splits across
                all local chips (and, multi-host, fans out pod-wide
                through the SPMD serving loop) instead of leaving N−1
                idle; bucket widths round up to mesh-divisible multiples
                (observable at /metrics engine.mesh). --no-mesh restores
                the single-device bucket programs for A/B
  --fallback-budget-s
                with --supervise-engine: wall-time budget per host-oracle
                fallback solve while DEGRADED/LOST (default 30 s) — an
                adversarial 16×16/25×25 board answers a clean 503 instead
                of pinning a host core on the oracle's exponential tail;
                0 disables the budget
  --no-obs      disable the request-lifecycle tracing plane (obs/): span
                recording across admission→coalesce→device→verify, the
                X-Timing breakdown, the /metrics obs block + stage
                histograms, and the incident flight recorder (its HTTP
                trigger 404s). X-Request-Id echo and the /metrics.prom
                rendering of the remaining blocks stay — ids correlate
                retries whether or not spans are recorded. ON by default:
                the plane costs ~15 µs/request (bench.py --mode
                obs-overhead holds the throughput A/B) and is the node's
                black box
  --slo / --slo-fast-burn
                declarative latency objectives (obs/slo.py, repeatable:
                --slo latency_p99_ms=500@99.9) evaluated as 5m/1h burn
                rates from the stage histograms; the 'slo' /metrics
                block + prom gauges carry them, and a fast-burn crossing
                (both windows over the bar) triggers the incident
                flight-recorder dump — alert-triggered, not just
                crash-triggered. With --metrics, GET /metrics/cluster
                (+ .prom) renders the gossip-aggregated fleet view
                (obs/cluster.py) and GET /debug/trace exports the span
                ring as Perfetto-loadable trace-event JSON (obs/export.py)
  --flightrecord-dir
                where incident flight-recorder dumps land (breaker trip,
                shed storm, SIGUSR2, POST /debug/flightrecord); env default
                SUDOKU_FLIGHTRECORD_DIR, else ./flightrecords
  --device-trace-dir / --device-trace-calls
                jax.profiler hook: record ONE warmup pass and the first N
                supervised device calls (default 4) as XLA trace artifacts
                into the dir — a TPU window run leaves profiler evidence
                with no code edits (capture state rides warm_info() on
                /metrics)
  --compile-cache-dir / --warmup-budget-s
                cold-start compiler plane (compilecache/, engine.warmup):
                the cache dir roots jax's persistent XLA cache plus the
                explicit AOT artifact store (env default
                SUDOKU_COMPILE_CACHE_DIR), so compiles paid once are disk
                reads forever after; the warmup budget bounds background
                ladder widening so a short TPU claim window spends its
                seconds on the buckets the bench will hit — tier 0 (the
                smallest + coalescer-preferred buckets) always compiles,
                and /solve is servable the moment it has compiled
"""

from __future__ import annotations

import argparse
import logging
import os
import threading
import time


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Sudoku Solver Node", conflict_handler="resolve"
    )
    parser.add_argument("-p", type=int, default=8001, help="HTTP port")
    parser.add_argument("-s", type=int, default=7000, help="P2P port")
    parser.add_argument("-a", help="Anchor node address (host:port)")
    parser.add_argument(
        "-h", type=float, default=1, help="Handicap (delay in ms) for validation"
    )
    parser.add_argument("--host", default="127.0.0.1", help="bind address")
    parser.add_argument(
        "--mesh-peers",
        type=int,
        default=0,
        help="surface N TPU-core pseudo-peers at /network",
    )
    parser.add_argument("--no-warmup", action="store_true")
    parser.add_argument(
        "--buckets",
        default=None,
        help="comma-separated engine batch buckets (default 1,8,64,512,4096)",
    )
    parser.add_argument(
        "--board-size",
        type=int,
        default=9,
        choices=[4, 9, 16, 25],
        help="board edge length the engine serves (9, 16 hexadoku, or 25)",
    )
    parser.add_argument(
        "--solver-config",
        default=None,
        choices=["default", "legacy"],
        help="hot-loop preset (ops/config.SOLVER_PRESETS): 'legacy' "
        "restores the pre-PR7 solver loop (unpacked analysis, quartering "
        "compaction ladder) for A/B — xla backend only",
    )
    parser.add_argument(
        "--metrics", action="store_true", help="expose GET /metrics"
    )
    parser.add_argument(
        "--batch-api",
        action="store_true",
        help="expose POST /solve_batch (the engine's bucketed batch path "
        "over HTTP; opt-in — off keeps the reference 404 surface)",
    )
    parser.add_argument(
        "--serving-stats",
        action="store_true",
        help="add a 'serving' block (coalescer batch-fill / queue-depth / "
        "wait-time) to GET /stats; opt-in — off keeps the reference "
        "stats body byte-identical",
    )
    parser.add_argument(
        "--no-coalesce",
        action="store_true",
        help="disable the request-coalescing micro-batch scheduler: every "
        "/solve pays its own batch-1 device call (the pre-coalescer "
        "serving path; for A/B measurement)",
    )
    parser.add_argument(
        "--seed-serving",
        action="store_true",
        help="serve exactly like the seed for A/B measurement: requests "
        "serialized behind one lock, no coalescer, HTTP/1.0 transport on "
        "the stock 5-deep accept queue (bench.py --mode concurrent's "
        "baseline phase)",
    )
    parser.add_argument(
        "--coalesce-max-wait-ms",
        type=float,
        default=2.0,
        help="longest a lone request waits for batch co-riders before its "
        "bucket dispatches anyway (default 2 ms)",
    )
    parser.add_argument(
        "--adaptive-coalesce",
        action="store_true",
        help="scale the coalescer wait budgets with the measured arrival "
        "rate (serving/load.py): near-zero wait when idle (a lone request "
        "dispatches immediately), the configured budgets under load. Off "
        "by default: fixed budgets",
    )
    parser.add_argument(
        "--admission-capacity",
        type=int,
        default=0,
        help="overload control (serving/admission.py): max admitted "
        "/solve requests in flight; arrivals past it answer 429 + "
        "Retry-After instead of queueing without bound. 0 (default) "
        "disables the pending bound",
    )
    parser.add_argument(
        "--default-deadline-ms",
        type=float,
        default=0.0,
        help="latency budget for /solve requests without an X-Deadline-Ms "
        "header: requests whose projected queue wait exceeds it are shed "
        "429 at arrival, and admitted requests that expire waiting are "
        "dropped before the device runs them. 0 (default) = no deadline",
    )
    parser.add_argument(
        "--supervise-engine",
        action="store_true",
        help="failure-domain supervision for the engine/device plane "
        "(serving/health.py): a watchdog bounds device-call wall time, a "
        "circuit breaker drives WARMING/HEALTHY/DEGRADED/LOST, "
        "DEGRADED/LOST serve correct answers from a bounded host-oracle "
        "fallback (X-Degraded header) while half-open probes — verified "
        "round-trip solves — re-admit the device. Off by default: no "
        "supervision, byte-identical serving",
    )
    parser.add_argument(
        "--watchdog-budget-s",
        type=float,
        default=30.0,
        help="with --supervise-engine: wall-time budget per device call "
        "before it is declared hung (bucket quarantined, breaker fed)",
    )
    parser.add_argument(
        "--breaker-threshold",
        type=int,
        default=3,
        help="with --supervise-engine: consecutive failures before "
        "DEGRADED escalates to LOST (engine rebuild + probe-gated "
        "re-admission)",
    )
    parser.add_argument(
        "--probe-interval-s",
        type=float,
        default=2.0,
        help="with --supervise-engine: half-open probe cadence while the "
        "breaker is open",
    )
    parser.add_argument(
        "--fallback-concurrency",
        type=int,
        default=2,
        help="with --supervise-engine: max concurrent host-oracle "
        "fallback solves while DEGRADED/LOST (bounded — the fallback "
        "keeps the node answering, it does not pretend the host is a "
        "TPU)",
    )
    parser.add_argument(
        "--fallback-budget-s",
        type=float,
        default=30.0,
        help="with --supervise-engine: wall-time budget per host-oracle "
        "fallback solve (serving/health.py); a degraded node answers "
        "503 on boards whose MRV refutation runs past it instead of "
        "pinning a host core (0 = unbudgeted)",
    )
    parser.add_argument(
        "--no-mesh",
        action="store_true",
        help="disable mesh-parallel bucket serving: single-device bucket "
        "programs even with >1 device (the pre-ISSUE-8 serving substrate, "
        "for A/B). Default: with more than one device, bucket batches "
        "dispatch through shard_map over every local chip "
        "(parallel/shard.py) and bucket widths round to mesh-divisible "
        "multiples",
    )
    parser.add_argument(
        "--http-workers",
        type=int,
        default=128,
        help="connection-worker pool bound for the serving transport "
        "(net/fastserve.py): a connection flood exhausts a queue, not "
        "the process thread table",
    )
    parser.add_argument(
        "--coalesce-max-batch",
        type=int,
        default=None,
        help="cap boards per coalesced device call (default: the largest "
        "bucket). Set to the backend's efficient width — e.g. 8 on the "
        "CPU fallback, where a wide batch of mixed boards pays the worst "
        "board's iterations across the full width (engine.py rationale)",
    )
    parser.add_argument(
        "--no-continuous",
        action="store_true",
        help="disable continuous batching: the coalesced serving path "
        "falls back to the closed-loop run-to-completion dispatcher "
        "instead of the open-loop segmented lane pool with mid-flight "
        "refill (parallel/coalescer.py; the A/B escape hatch of "
        "bench.py --mode continuous). Answers are bit-identical either "
        "way",
    )
    parser.add_argument(
        "--no-segment-pipeline",
        action="store_true",
        help="disable the pipelined segment boundary (PR 15): the "
        "continuous driver falls back to the PR 12 boundary "
        "byte-for-byte — full packed-row fetch every segment, no "
        "buffer donation, strictly serial boundaries (the A/B escape "
        "hatch of bench.py --mode continuous). Answers are "
        "bit-identical either way",
    )
    parser.add_argument(
        "--deep-lane-cap",
        type=int,
        default=0,
        help="with continuous batching: max lanes boards resident past "
        "a few segment boundaries may hold while fresh demand queues — "
        "overage evicts to the deep-retry net so deep-heavy overload "
        "stops squeezing refill goodput (parallel/coalescer.py). "
        "0 (default) = no cap",
    )
    parser.add_argument(
        "--no-answer-cache",
        action="store_true",
        help="disable the canonical-form answer cache (cache/): every "
        "request pays full admission + dispatch even for a repeat or a "
        "symmetry of an already-answered puzzle (the A/B baseline of "
        "bench.py --mode cache)",
    )
    parser.add_argument(
        "--answer-cache-capacity",
        type=int,
        default=4096,
        help="answer-cache entries across all shards (one entry serves "
        "a puzzle's whole symmetry orbit); per-shard LRU eviction past "
        "it",
    )
    parser.add_argument(
        "--cache-fetch-timeout-ms",
        type=float,
        default=250.0,
        help="how long a local cache miss on a peer-advertised hot key "
        "waits for the peer's cache_answer before dispatching normally "
        "(cache/gossip.py); 0 disables peer fetching",
    )
    parser.add_argument(
        "--segment-iters",
        type=int,
        default=None,
        help="lockstep iterations per continuous-batching segment (the "
        "sweepable k; default: ops.config.SEGMENT per board size). "
        "Smaller = finished lanes refill sooner, larger amortizes "
        "segment dispatch overhead",
    )
    parser.add_argument(
        "--compile-cache-dir",
        default=os.environ.get("SUDOKU_COMPILE_CACHE_DIR") or None,
        help="root of the persistent compile plane (compilecache/): "
        "<dir>/xla hosts jax's persistent compilation cache, <dir>/aot "
        "the explicit AOT executable store warmup loads verified "
        "artifacts from (and bakes new ones into). Env default: "
        "SUDOKU_COMPILE_CACHE_DIR. Unset (default): no persistence, "
        "every process compiles from scratch",
    )
    parser.add_argument(
        "--warmup-budget-s",
        type=float,
        default=0.0,
        help="bound the background warmup's ladder widening to this many "
        "seconds: tier 0 (smallest + coalescer-preferred buckets) always "
        "compiles and flips serving warm; buckets past the budget are "
        "skipped and requests tile over the warm widths instead "
        "(engine.warmup). 0 (default) = no budget, warm the full ladder",
    )
    parser.add_argument(
        "--profile-dir", default=None, help="jax.profiler trace output dir"
    )
    parser.add_argument(
        "--no-obs",
        action="store_true",
        help="disable the request-lifecycle tracing plane (obs/): span "
        "recording, the X-Timing breakdown, the /metrics obs block and "
        "stage histograms, and the incident flight recorder (X-Request-Id "
        "echo stays — ids correlate retries regardless). On by default "
        "(bench.py --mode obs-overhead holds the cost claim)",
    )
    parser.add_argument(
        "--slo",
        action="append",
        default=[],
        metavar="NAME=MS@PCT",
        help="declarative latency objective, repeatable (obs/slo.py): "
        "e.g. --slo latency_p99_ms=500@99.9 means 99.9%% of requests "
        "under 500 ms; [stage_] prefixes pick a span stage "
        "(device_latency_p99_ms=50@99). Evaluated as 5m/1h burn rates "
        "from the stage histograms, exposed as an 'slo' /metrics block "
        "+ prom gauges; a fast-burn crossing (both windows over 14.4x "
        "budget rate) records a flight-recorder event and triggers the "
        "incident auto-dump. Requires the tracing plane (not --no-obs)",
    )
    parser.add_argument(
        "--no-autopilot",
        action="store_true",
        help="disable the fleet autopilot (serving/autopilot.py, ISSUE "
        "14): no burn-aware admission tightening, no telemetry-weighted "
        "farm ranking, no hedged dispatch, no join deferral — the PR 13 "
        "serving surface byte-identically. ON by default: the loops "
        "no-op gracefully when their inputs (SLO engine, admission, "
        "telemetry) are absent",
    )
    parser.add_argument(
        "--no-autopilot-admission",
        action="store_true",
        help="disable ONLY the burn-aware admission loop (an SLO "
        "fast-burn edge tightening the projected-wait shed budget, "
        "relaxing with hysteresis on recovery)",
    )
    parser.add_argument(
        "--no-autopilot-farm",
        action="store_true",
        help="disable ONLY telemetry-weighted farm ranking (masters "
        "fall back to the PR 13 sorted dispatch order; the PR 5 "
        "LOST-skip always applies)",
    )
    parser.add_argument(
        "--no-autopilot-hedge",
        action="store_true",
        help="disable ONLY hedged dispatch (a farm cell straggling past "
        "the measured farm-task p99 is no longer duplicated to an idle "
        "peer)",
    )
    parser.add_argument(
        "--no-autopilot-join",
        action="store_true",
        help="disable ONLY elastic membership (the joiner dials its "
        "anchor immediately instead of deferring until /readyz would "
        "pass, and skips the hot-set cache prewarm)",
    )
    parser.add_argument(
        "--hedge-budget-pct",
        type=float,
        default=25.0,
        help="with the autopilot's hedge loop: lifetime hedge dispatches "
        "stay under this percentage of primary dispatches (floor: one "
        "outstanding hedge) — the tail-at-scale bound that keeps "
        "straggler-chasing from amplifying an overload",
    )
    parser.add_argument(
        "--slo-windows",
        default=None,
        metavar="SHORT_S,LONG_S",
        help="with --slo: override the burn-rate window pair in seconds "
        "(default 300,3600 — the SRE-workbook 5m/1h shape). Short "
        "windows (e.g. 5,15) make fast-burn detection and recovery "
        "observable inside a short chaos run (bench.py --mode chaos)",
    )
    parser.add_argument(
        "--chaos-injector",
        action="store_true",
        help="arm an engine-seam fault injector (utils/faults."
        "EngineFaultInjector) and expose POST /debug/faults to drive it "
        "(fail_next / delay_s / poison_bucket / clear) — the chaos "
        "bench's remote arming surface. Off by default: the route 404s "
        "and no injector exists",
    )
    parser.add_argument(
        "--slo-fast-burn",
        type=float,
        default=14.4,
        help="with --slo: the fast-burn page bar in multiples of the "
        "sustainable budget-spend rate (default 14.4 — the classic "
        "2%%-of-monthly-budget-in-an-hour alert)",
    )
    parser.add_argument(
        "--flightrecord-dir",
        default=os.environ.get("SUDOKU_FLIGHTRECORD_DIR") or "flightrecords",
        help="directory incident flight-recorder dumps are written to "
        "(breaker trip, shed storm, SIGUSR2, POST /debug/flightrecord). "
        "Env default: SUDOKU_FLIGHTRECORD_DIR",
    )
    parser.add_argument(
        "--device-trace-dir",
        default=None,
        help="record ONE warmup pass and the first N supervised device "
        "calls (--device-trace-calls) as jax.profiler/XLA trace artifacts "
        "into this dir; capture state rides warm_info() at /metrics",
    )
    parser.add_argument(
        "--device-trace-calls",
        type=int,
        default=4,
        help="with --device-trace-dir: how many supervised device calls "
        "to capture after warmup (default 4)",
    )
    parser.add_argument(
        "--failure-timeout",
        type=float,
        default=5.0,
        help="declare a silent neighbor dead after this many seconds (0=off)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="jax.distributed coordinator host:port (multi-host pod slice)",
    )
    parser.add_argument("--num-hosts", type=int, default=1)
    parser.add_argument("--host-id", type=int, default=0)
    # --backend pallas was removed from the serving CLI (VERDICT r4 task 3
    # / weak #3): the Mosaic kernel cannot run SERVING_CONFIG (no locked
    # sets / waves — engine.py refuses the flags) and no environment to
    # date has completed a Pallas TPU compile (docs/DESIGN.md), so offering
    # it here silently served a different, weaker search configuration
    # than the benched one. The kernel remains available programmatically
    # (SolverEngine(backend="pallas"), ops.pallas_solver) as a documented
    # experiment, parity-tested in interpret mode; benchmarks/exp_pallas.py
    # and the TPU session's pallas phase produce the on-chip comparison the
    # moment a terminal can compile it.
    parser.add_argument(
        "--backend",
        default="xla",
        choices=["xla"],
        help="engine batch kernel (the XLA compacted lockstep solver)",
    )
    parser.add_argument(
        "--frontier",
        type=int,
        default=0,
        metavar="STATES_PER_DEVICE",
        help="route single-board /solve through the mesh-sharded search-"
        "frontier race with this many speculative states per chip "
        "(0 = off: bucket-1 batch solve)",
    )
    parser.add_argument(
        "--frontier-route",
        default="auto",
        choices=["auto", "always"],
        help="with --frontier: 'auto' (default) answers easy requests from "
        "a short bucket-path probe and escalates only deep-search boards "
        "to the race (measured crossover policy, engine.py); 'always' "
        "races every request",
    )
    parser.add_argument(
        "--frontier-escalate-iters",
        type=int,
        default=512,
        help="auto-route probe budget in lockstep iterations before a "
        "request escalates to the frontier race",
    )
    parser.add_argument(
        "--frontier-handoff",
        action="store_true",
        help="seed escalated races from the auto-route probe's unexplored "
        "subtrees instead of restarting from the board's root. Off by "
        "default: measured slower (benchmarks/exp_handoff.py — the root "
        "restart's fresh MRV split beats the probe's chain decomposition); "
        "kept as an opt-in for deployments where seeding RTTs dominate",
    )
    parser.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="force the JAX platform (default: the environment's default "
        "backend). Uses jax.config pre-init — env-var routes are "
        "unreliable where a sitecustomize pins JAX_PLATFORMS",
    )
    return parser


def main(argv=None) -> None:
    from .http_api import make_http_server
    from .node import P2PNode

    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s - %(levelname)s - %(message)s"
    )

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.coordinator:
        # Pod-slice mode: every host runs this same CLI; XLA collectives ride
        # ICI/DCN underneath while the UDP/HTTP control plane stays host-side.
        import jax

        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_hosts,
            process_id=args.host_id,
        )

    from ..engine import SolverEngine
    from ..ops import spec_for_size

    kwargs = {
        "spec": spec_for_size(args.board_size),
        "backend": args.backend,
        "coalesce": not (args.no_coalesce or args.seed_serving),
        "coalesce_max_wait_s": args.coalesce_max_wait_ms / 1e3,
        "coalesce_max_batch": args.coalesce_max_batch,
        "coalesce_adaptive": args.adaptive_coalesce,
        # continuous batching (ISSUE 12): default ON for the coalesced
        # path (None resolves ops.config.CONTINUOUS_SERVING); the flag
        # is the closed-loop A/B escape hatch
        "continuous": False if args.no_continuous else None,
        "segment_iters": args.segment_iters,
        # pipelined segment boundary (PR 15): default ON with continuous
        # (None resolves ops.config.SEGMENT_PIPELINE); the flag restores
        # the PR 12 boundary byte-for-byte
        "segment_pipeline": False if args.no_segment_pipeline else None,
        "deep_lane_cap": args.deep_lane_cap,
        "compile_cache_dir": args.compile_cache_dir,
        "solver_config": args.solver_config,
    }
    if args.buckets:
        kwargs["buckets"] = tuple(int(b) for b in args.buckets.split(","))
    multi_host = bool(args.coordinator) and args.num_hosts > 1
    serving_loop = None
    mesh_serving = not args.no_mesh and args.backend == "xla"
    mesh_fanout = False
    if mesh_serving:
        import jax

        if multi_host:
            # Pod-slice mesh serving: the engine's OWN programs run on
            # this host's local devices (every host warms and serves them
            # independently — a global collective cannot be compiled
            # outside the lockstep loop), while bucket widths round to
            # the GLOBAL device count so leader fan-out batches divide
            # the pod-wide mesh (parallel/serving_loop.py batch lane).
            # The fan-out lane's broadcasts ARE multiprocess collectives,
            # unimplemented on the CPU backend (jax 0.4.37) — arming it
            # there would kill the loop (and the leader) at the first
            # warm, so CPU pods keep local-mesh serving only (the sim
            # harness is unaffected: fake devices are single-process).
            from ..parallel import default_mesh

            local = jax.local_devices()
            if len(local) > 1:
                kwargs["mesh"] = default_mesh(local)
            mesh_fanout = jax.default_backend() != "cpu"
            if mesh_fanout:
                kwargs["bucket_multiple"] = jax.device_count()
            else:
                logging.getLogger(__name__).warning(
                    "mesh serving: CPU backend cannot run cross-process "
                    "collectives — leader batch fan-out disabled, each "
                    "host serves its local mesh"
                )
        else:
            # single host: every bucket program shard_maps over all local
            # devices when more than one is present (engine mesh="auto")
            kwargs["mesh"] = "auto"
    if args.frontier > 0 and not multi_host:
        from ..parallel import default_mesh

        kwargs["frontier_mesh"] = default_mesh()
        kwargs["frontier_states_per_device"] = args.frontier
    if args.frontier > 0:
        kwargs["frontier_route"] = args.frontier_route
        kwargs["frontier_escalate_iters"] = args.frontier_escalate_iters
        kwargs["frontier_handoff"] = args.frontier_handoff
    engine = SolverEngine(**kwargs)
    if multi_host and (args.frontier > 0 or mesh_fanout):
        # Collectives over the global mesh — the frontier race and (ISSUE
        # 8) the coalesced-batch fan-out — must be entered by every host
        # in lockstep: the SPMD serving loop broadcasts each request and
        # the leader's HTTP thread feeds it (parallel/serving_loop.py).
        # Non-leader hosts serve /solve from their local bucket path.
        from ..parallel import FrontierServingLoop, default_mesh

        # every solver knob mirrors the engine's resolved SERVING_CONFIG
        # values, so the race serves the exact benched configuration
        serving_loop = FrontierServingLoop(
            default_mesh(),
            engine.spec,
            states_per_device=max(args.frontier, 1),
            max_depth=engine.max_depth,
            locked=engine.locked_candidates,
            waves=engine.waves,
            naked_pairs=engine.naked_pairs,
        )
        if mesh_fanout:
            # arm the batch lane on EVERY host before the loop starts:
            # the sharded bucket program all hosts will enter when a
            # leader batch header lands
            serving_loop.enable_batch_fanout(engine)
        serving_loop.start(warm_race=args.frontier > 0)
        if serving_loop.is_leader:
            if args.frontier > 0:
                engine.frontier_runner = serving_loop.solve
            engine.frontier_loop = serving_loop
            if mesh_fanout:
                # leader: bucket dispatches ride the loop so every pod
                # host's devices join each coalesced batch. The global
                # program retraces per bucket width, and a
                # first-at-this-width batch compiling inside the serving
                # path would hold the loop's mutex for the whole pod-wide
                # compile (and, supervised, read as a hung call: the
                # width is already warmup-marked by the LOCAL
                # engine.warmup, so the first-call hang exemption would
                # not apply) — so warm EVERY width, tiered like
                # engine.warmup: the smallest synchronously (serving is
                # provably live before the HTTP server opens), the rest
                # in the background in ladder order (each warm owns the
                # loop mutex only for its own compile; an early real
                # batch at a not-yet-warm width just queues behind it)
                engine.mesh_runner = serving_loop.solve_padded
                serving_loop.warm_batch_fanout(
                    engine.buckets[0], engine.max_iters
                )

                def _warm_remaining_widths():
                    for _b in engine.buckets[1:]:
                        try:
                            serving_loop.warm_batch_fanout(
                                _b, engine.max_iters
                            )
                        except Exception:  # noqa: BLE001 — warm only
                            logging.getLogger(__name__).warning(
                                "background fan-out warm failed at "
                                "width %d", _b, exc_info=True,
                            )
                            return

                if len(engine.buckets) > 1:
                    threading.Thread(
                        target=_warm_remaining_widths,
                        daemon=True,
                        name="fanout-warm",
                    ).start()
    from ..utils.profiling import RequestMetrics

    # request-lifecycle tracing plane (obs/, ISSUE 6): default ON — the
    # spans, the flight recorder, and the Prometheus stage histograms
    # are the node's black box, and the plane's cost is the feature's
    # own claim (bench.py --mode obs-overhead). --no-obs is the overhead
    # A/B's baseline: no span recording anywhere (X-Request-Id echo is
    # unconditional on both arms — retries must correlate regardless).
    tracer = None
    flight = None
    slo = None
    if not args.no_obs:
        from ..obs import FlightRecorder, Tracer

        flight = FlightRecorder(dump_dir=args.flightrecord_dir)
        tracer = Tracer(recorder=flight)
        if args.slo:
            # SLO burn-rate engine (ISSUE 10, obs/slo.py): objectives
            # parse at startup (a malformed spec must fail the boot, not
            # the claim window), evaluation rides Tracer.finish
            from ..obs.slo import DEFAULT_WINDOWS_S, SloEngine, parse_slo

            windows = DEFAULT_WINDOWS_S
            if args.slo_windows:
                try:
                    windows = tuple(
                        float(w) for w in args.slo_windows.split(",")
                    )
                    if len(windows) != 2 or min(windows) <= 0:
                        raise ValueError
                except ValueError:
                    raise SystemExit(
                        f"--slo-windows wants SHORT_S,LONG_S (got "
                        f"{args.slo_windows!r})"
                    ) from None
            slo = SloEngine(
                tracer.stages,
                [parse_slo(s) for s in args.slo],
                recorder=flight,
                windows_s=windows,
                fast_burn_threshold=args.slo_fast_burn,
            )
            tracer.slo = slo
    elif args.slo:
        raise SystemExit(
            "--slo needs the tracing plane (stage histograms) — "
            "remove --no-obs"
        )

    admission = None
    if args.admission_capacity > 0 or args.default_deadline_ms > 0:
        from ..serving import AdmissionController

        admission = AdmissionController(
            capacity=args.admission_capacity,
            default_deadline_ms=args.default_deadline_ms,
        )
    if args.supervise_engine:
        from ..serving.health import EngineSupervisor

        supervisor = EngineSupervisor(
            engine,
            watchdog_budget_s=args.watchdog_budget_s,
            breaker_threshold=args.breaker_threshold,
            probe_interval_s=args.probe_interval_s,
            fallback_concurrency=args.fallback_concurrency,
            fallback_budget_s=args.fallback_budget_s or None,
        )
        if admission is not None:
            # every regime change — device lost AND device re-admitted —
            # re-anchors the capacity estimator on the throughput the
            # node can actually deliver NOW (serving/admission.py)
            supervisor.add_transition_callback(
                lambda _old, _new: admission.reanchor()
            )
        if flight is not None:
            # breaker trips / watchdog hangs land in the event ring and
            # dump the black box (obs/flight.py)
            flight.attach_supervisor(supervisor)
    node = P2PNode(
        args.host,
        args.s,
        anchor_node=args.a,
        handicap=args.h / 100,
        engine=engine,
        mesh_peer_count=args.mesh_peers,
        failure_timeout=args.failure_timeout,
        # ONE recording machinery: with the tracing plane on, the node's
        # per-route recorder IS the tracer's (obs/histo.RouteMetrics)
        metrics=tracer.routes if tracer is not None else RequestMetrics(),
        serialize_solves=args.seed_serving,
        admission=admission,
    )
    node.tracer = tracer
    node.flight = flight
    node.slo = slo
    if not args.no_answer_cache:
        # canonical-form answer cache (cache/, ISSUE 13; default ON):
        # front-door lookup in the /solve and /solve_batch route cores,
        # verified-only writes, hot-set gossip on the stats heartbeat,
        # peer fetch on advertised keys. --no-answer-cache is the A/B
        # baseline (bench.py --mode cache)
        from ..cache import AnswerCache, CacheGossip

        node.answer_cache = AnswerCache(
            capacity=max(1, args.answer_cache_capacity)
        )
        node.cache_gossip = CacheGossip(
            node.answer_cache,
            node,
            fetch_timeout_s=max(0.0, args.cache_fetch_timeout_ms) / 1e3,
        )
    if tracer is not None:
        # fleet telemetry publisher (ISSUE 10, obs/cluster.py): this
        # node's digest rides every stats-gossip heartbeat (rebuilt at
        # most 1/s) so any peer can render GET /metrics/cluster
        from ..obs.cluster import TelemetryPublisher

        node.telemetry = TelemetryPublisher(node)
    if args.chaos_injector:
        # chaos-harness arming surface (ISSUE 14): an engine-seam fault
        # injector driveable over POST /debug/faults — the PR 5
        # injectors reachable on a LIVE fleet member, so bench.py
        # --mode chaos can poison/slow a node's device path mid-run
        from ..utils.faults import EngineFaultInjector

        engine.fault_injector = EngineFaultInjector()
        node.chaos_routes = True
    autopilot = None
    if not args.no_autopilot:
        # fleet autopilot (serving/autopilot.py, ISSUE 14; default ON):
        # burn-aware admission tightening, telemetry-weighted farm
        # ranking, hedged dispatch, elastic membership. Each loop
        # no-ops when its inputs are absent (no SLO engine → no
        # tightening; no telemetry → neutral ranking), and each has its
        # own escape hatch. The join loop is tied to warmup: a
        # --no-warmup node never flips tier-0 warm, so deferring its
        # join on readiness would only burn the defer horizon.
        from ..serving.autopilot import Autopilot

        autopilot = Autopilot(
            node,
            admission=admission,
            slo=slo,
            admission_loop=not args.no_autopilot_admission,
            farm_loop=not args.no_autopilot_farm,
            hedge_loop=not args.no_autopilot_hedge,
            join_loop=(
                not args.no_autopilot_join and not args.no_warmup
            ),
            hedge_budget_frac=max(0.0, args.hedge_budget_pct) / 100.0,
        )
        node.autopilot = autopilot
        autopilot.start()
    if flight is not None:
        import signal

        try:
            # operator dump trigger: kill -USR2 <pid> writes the flight
            # record without touching the HTTP surface
            signal.signal(
                signal.SIGUSR2,
                lambda _sig, _frm: flight.dump(reason="sigusr2"),
            )
        except (ValueError, AttributeError, OSError):
            # non-main thread (embedding) or a platform without SIGUSR2:
            # the HTTP trigger still works
            pass
    if args.profile_dir:
        node.engine.profile_dir = args.profile_dir
    if args.device_trace_dir:
        # jax.profiler hook (ISSUE 6 satellite): warmup + the first N
        # supervised device calls leave XLA trace artifacts
        engine.arm_device_trace(
            args.device_trace_dir, calls=args.device_trace_calls
        )
    if not args.no_warmup:
        # pre-compile the serving buckets so the first /solve is warm
        # (p50 <5 ms contract, engine.SolverEngine.warmup). Tiered: the
        # thread flips `warmed` the moment tier 0 compiles, then widens
        # the ladder — bounded by --warmup-budget-s when set
        threading.Thread(
            target=node.engine.warmup,
            kwargs={"budget_s": args.warmup_budget_s or None},
            daemon=True,
        ).start()

    def _freeze_after_warmup():
        # Serving-process GC hygiene: once the ladder is warm the heap is
        # huge (jax + compiled programs) and effectively immortal, yet
        # every ~7k request-path container allocations would drag a full
        # collection over it. Freezing the post-warmup heap moves it to
        # the permanent generation, so steady-state GC only scans the
        # young per-request objects — this keeps request-path features
        # (coalescer futures, tracing spans) allocation-cheap instead of
        # GC-amplified.
        import gc

        if not args.no_warmup:
            # wait for the ladder (bounded: a budget-cut warmup never
            # flips fully_warmed — freeze what exists by the horizon)
            deadline = time.monotonic() + 600.0
            while (
                not engine.fully_warmed and time.monotonic() < deadline
            ):
                time.sleep(1.0)
        gc.collect()
        gc.freeze()

    threading.Thread(target=_freeze_after_warmup, daemon=True).start()

    httpd = make_http_server(
        node, args.host, args.p,
        expose_metrics=args.metrics,
        expose_batch=args.batch_api,
        expose_serving=args.serving_stats,
        legacy_transport=args.seed_serving,
        max_workers=args.http_workers,
    )
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    http_thread.start()
    try:
        node.run()
    finally:
        httpd.shutdown()
        if autopilot is not None:
            autopilot.close()
        engine.close()  # drain the coalescer (in-flight futures resolve)
        if serving_loop is not None and serving_loop.is_leader:
            serving_loop.stop()
