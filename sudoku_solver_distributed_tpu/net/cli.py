"""Node CLI — flag-compatible with the reference (reference node.py:715-730).

Same four flags with the same meanings and defaults:
  -p  HTTP port (default 8001)
  -s  P2P/UDP port (default 7000)
  -a  anchor node "host:port"
  -h  handicap in ms, divided by 100 into base_delay seconds (the reference's
      conversion, node.py:726); argparse uses conflict_handler='resolve' so
      -h means handicap, not help, exactly as the reference does.

Extensions (defaults preserve reference behavior):
  --host        bind address (default 127.0.0.1 — the reference hardcodes its
                authors' LAN IP 192.168.1.126, node.py:708/726, and cannot
                start anywhere else [SURVEY.md §2 verified live]; a
                configurable host is the defect fix)
  --mesh-peers  N: surface N TPU-core pseudo-peers at /network (the
                north-star mapping, BASELINE.json); default 0
  --no-warmup   skip engine pre-compilation (faster start, slower first solve)
"""

from __future__ import annotations

import argparse
import logging
import threading


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="Sudoku Solver Node", conflict_handler="resolve"
    )
    parser.add_argument("-p", type=int, default=8001, help="HTTP port")
    parser.add_argument("-s", type=int, default=7000, help="P2P port")
    parser.add_argument("-a", help="Anchor node address (host:port)")
    parser.add_argument(
        "-h", type=float, default=1, help="Handicap (delay in ms) for validation"
    )
    parser.add_argument("--host", default="127.0.0.1", help="bind address")
    parser.add_argument(
        "--mesh-peers",
        type=int,
        default=0,
        help="surface N TPU-core pseudo-peers at /network",
    )
    parser.add_argument("--no-warmup", action="store_true")
    parser.add_argument(
        "--buckets",
        default=None,
        help="comma-separated engine batch buckets (default 1,8,64,512,4096)",
    )
    return parser


def main(argv=None) -> None:
    from .http_api import make_http_server
    from .node import P2PNode

    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s - %(levelname)s - %(message)s"
    )

    engine = None
    if args.buckets:
        from ..engine import SolverEngine

        engine = SolverEngine(
            buckets=tuple(int(b) for b in args.buckets.split(","))
        )
    node = P2PNode(
        args.host,
        args.s,
        anchor_node=args.a,
        handicap=args.h / 100,
        engine=engine,
        mesh_peer_count=args.mesh_peers,
    )
    if not args.no_warmup:
        # pre-compile the serving buckets so the first /solve is warm
        # (p50 <5 ms contract, engine.SolverEngine.warmup)
        threading.Thread(target=node.engine.warmup, daemon=True).start()

    httpd = make_http_server(node, args.host, args.p)
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    http_thread.start()
    try:
        node.run()
    finally:
        httpd.shutdown()
