"""Lean threaded HTTP/1.1 transport for the serving hot path.

`http.server`'s BaseHTTPRequestHandler costs ~1-2 ms of pure-Python (and
GIL-held) time per request — request-line regex, an email.parser pass over
the headers, date formatting for every response. On a CPU-fallback host
that overhead, not the device, is the serving ceiling: the coalescer
merges device work so well (parallel/coalescer.py) that the transport
becomes the bottleneck (measured ~330 puzzles/s flat with http.server vs
~2700 boards/s of warm bucket-8 device capacity on 2 cores).

This module is the matching inference-stack transport: a BOUNDED worker
pool (lazily grown to ``max_workers``) serving keep-alive connections off
a shared accept queue — each worker reads requests from one buffered
socket file, parsing just the request line + the few headers that matter
(Content-Length / Transfer-Encoding / Connection / X-Deadline-Ms), and
answers from a pre-baked header template. The pool bound means a
connection flood exhausts a queue, not the process's thread table
(serving/admission.py is the request-level guard above it). Route handling and response BODIES are the
exact shared cores in http_api.py (`solve_route`, `solve_batch_route`,
`stats_payload`, `metrics_payload`), so the serving surface stays
byte-identical to the reference no matter which transport carried it —
the A/B in `bench.py --mode concurrent` measures this stack against the
seed's (`--seed-serving` keeps the stock http.server + HTTP/1.0 path).

Framing rules match the stock handler's `_read_body`: a request whose
body cannot be consumed (chunked transfer, malformed/negative
Content-Length, over the size cap) answers 400 and closes — leftover
body bytes on a persistent connection would be parsed as the next
request's start line. Unknown POST paths also close, keeping the stock
handler's contract (tests/test_net_node.py keep-alive suite runs against
whichever transport `make_http_server` returns).
"""

from __future__ import annotations

import json
import logging
import queue
import socket
import threading
import time

from . import http_api

logger = logging.getLogger(__name__)

_REASONS = {
    200: b"OK",
    400: b"Bad Request",
    404: b"Not Found",
    429: b"Too Many Requests",
    503: b"Service Unavailable",
}
# generous cap for any route; /solve_batch's documented bound (http_api)
_MAX_BODY = http_api.MAX_BATCH_BYTES
_MAX_LINE = 65536
_MAX_HEADERS = 100
# accepted-but-unserved connections the pool will buffer before refusing:
# past this a connection flood is answered with an immediate close (one
# accept + one close per flood socket) instead of an unbounded fd pile.
# Kept SHORT relative to service rate on purpose — this queue sits AHEAD
# of the admission layer (serving/admission.py reads the request only
# once a worker picks the connection up), so its depth is invisible
# pre-admission queueing delay; a deep buffer here would quietly re-add
# the unbounded-lateness failure mode admission exists to remove
_CONN_BACKLOG = 256


class FastHTTPServer:
    """Drop-in for ThreadingHTTPServer's lifecycle surface:
    ``serve_forever()`` blocks (run it in a thread), ``shutdown()`` stops
    the accept loop, ``server_address`` carries the bound (host, port).

    Concurrency is a BOUNDED worker pool (``max_workers``, default 128),
    not a thread per connection: a connection flood can no longer mint
    threads without limit (PR 1's accept loop would — the one resource
    the transport handed out unmetered). Workers are spawned lazily, one
    per accepted connection until the cap, and each then serves
    keep-alive connections off a shared queue for the server's lifetime —
    a quiet test server holds a handful of threads, a saturated node
    holds exactly ``max_workers``. Connections beyond workers+backlog are
    closed at accept. ``shutdown`` stops new accepts and lets live
    requests finish (workers are daemon threads polling the shutdown
    flag)."""

    def __init__(
        self,
        p2p_node,
        host: str,
        port: int,
        *,
        expose_metrics: bool = False,
        expose_batch: bool = False,
        expose_serving: bool = False,
        max_workers: int = 128,
        conn_backlog: int = _CONN_BACKLOG,
    ):
        if max_workers < 1:
            raise ValueError("max_workers must be >= 1")
        self.p2p_node = p2p_node
        self.expose_metrics = expose_metrics
        self.expose_batch = expose_batch
        self.expose_serving = expose_serving
        self.max_workers = max_workers
        # deep accept queue, same rationale as the old _ThreadingHTTPServer:
        # the stock 5-deep backlog drops SYNs under a 64-client burst and
        # the overflow crawls through 1/3/7 s retransmit backoff
        self._sock = socket.create_server(
            (host, port), backlog=1024, reuse_port=False
        )
        self.server_address = self._sock.getsockname()
        self._shutdown = False
        self._conns: "queue.Queue" = queue.Queue(maxsize=max(1, conn_backlog))
        self._workers = 0
        self._pool_lock = threading.Lock()
        self.conns_refused = 0  # flood-closed at accept (benign race on int)

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self) -> None:
        while not self._shutdown:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                break  # listener closed by shutdown()
            try:
                self._conns.put_nowait(conn)
            except queue.Full:
                # workers saturated AND the hand-off queue full: refuse
                # rather than buffer without bound — the client sees an
                # immediate close/RST and can back off, instead of a
                # socket that hangs until some keep-alive slot frees
                self.conns_refused += 1
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            self._maybe_spawn_worker()

    def _maybe_spawn_worker(self) -> None:
        with self._pool_lock:
            if self._workers >= self.max_workers:
                return
            self._workers += 1
        threading.Thread(
            target=self._worker_loop,
            name=f"fastserve-worker-{self._workers}",
            daemon=True,
        ).start()

    def _worker_loop(self) -> None:
        # the catch-all matters: _serve_connection absorbs (OSError,
        # ValueError), but ANY other exception escaping a route core used
        # to kill this thread with _workers never decremented — enough
        # repeated faults wedged the pool permanently while accepts kept
        # queueing (ROADMAP fastserve-hardening (a)). Now a faulting
        # connection is logged and dropped, the worker lives on, and the
        # finally keeps the pool count honest even if the worker does die.
        try:
            while not self._shutdown:
                try:
                    conn = self._conns.get(timeout=1.0)
                except queue.Empty:
                    continue  # poll the shutdown flag; workers live with the server
                try:
                    self._serve_connection(conn)
                except Exception:  # noqa: BLE001 — fail the connection, not the pool
                    logger.exception(
                        "connection handler crashed — connection dropped, "
                        "worker continues"
                    )
        finally:
            with self._pool_lock:
                self._workers -= 1

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._sock.close()
        except OSError:
            pass
        # accepted-but-unserved connections must not leak past the
        # server's lifetime: close them instead of leaving clients
        # hanging on sockets no worker will ever pick up
        while True:
            try:
                conn = self._conns.get_nowait()
            except queue.Empty:
                break
            try:
                conn.close()
            except OSError:
                pass

    server_close = shutdown  # stock servers expose both

    # -- connection loop ---------------------------------------------------
    def _await_request_line(self, conn, rfile):
        """Block for the next request's first line in short slices.

        The between-requests idle wait is where a keep-alive connection
        can pin a worker: with the whole pool pinned by idle sessions, a
        newly accepted connection would otherwise starve in the hand-off
        queue for the full 300 s keep-alive allowance. Waiting in 5 s
        slices lets the worker yield (returning None closes this
        connection) as soon as another connection is queued, while a
        sole idle client still gets the full allowance. A timeout slice
        that fires with zero bytes buffered is safe; a client that
        stalls >5 s MID-line risks its connection (buffered-reader state
        after a timeout is undefined) — that trade replaces silent
        starvation of everyone else."""
        deadline = time.monotonic() + 300.0
        while not self._shutdown:
            conn.settimeout(5.0)
            try:
                line = rfile.readline(_MAX_LINE + 1)
            except TimeoutError:
                if not self._conns.empty() or time.monotonic() > deadline:
                    return None  # yield the worker / reap the idler
                continue
            conn.settimeout(30.0)  # per-read budget for the rest
            return line
        return None

    def _serve_connection(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb", -1)
        try:
            while not self._shutdown:
                line = self._await_request_line(conn, rfile)
                if line is None or not self._handle_one(conn, rfile, line):
                    break
        except (OSError, ValueError):
            pass  # client went away mid-request; nothing to answer
        finally:
            try:
                rfile.close()
            finally:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                conn.close()

    def _handle_one(self, conn, rfile, line: bytes) -> bool:
        """Serve one request (whose first line the worker already read in
        ``_await_request_line``); returns False when the connection is
        done."""
        if not line:
            return False  # client closed cleanly between requests
        if line in (b"\r\n", b"\n"):
            return True  # tolerate a stray blank line (RFC 9112 §2.2)
        t0 = time.perf_counter()
        parts = line.split()
        if len(parts) != 3 or len(line) > _MAX_LINE:
            return False  # not HTTP; drop the connection
        method, path, version = parts
        headers = {}
        for _ in range(_MAX_HEADERS):
            h = rfile.readline(_MAX_LINE + 1)
            if h in (b"\r\n", b"\n", b""):
                break
            if len(h) > _MAX_LINE or not h.endswith(b"\n"):
                # oversize or truncated header line: readline returned a
                # fragment, and the NEXT readline would re-parse its tail
                # as a forged header (e.g. a smuggled content-length that
                # desyncs keep-alive framing) — drop the connection
                return False
            key, sep, value = h.partition(b":")
            if sep:
                headers[key.strip().lower()] = value.strip()
        else:
            return False  # header flood; drop

        close = version == b"HTTP/1.0" or (
            headers.get(b"connection", b"").lower() == b"close"
        )

        # per-request observability context (ISSUE 6): every response
        # carries X-Request-Id (client-echoed or minted); X-Timing is the
        # client's opt-in to the span's stage breakdown
        req_id = http_api.ensure_request_id(headers.get(b"x-request-id"))
        want_timing = b"x-timing" in headers

        # body framing (mirrors the stock handler's _read_body contract)
        te = headers.get(b"transfer-encoding", b"").lower()
        try:
            content_length = int(headers.get(b"content-length", 0))
        except ValueError:
            content_length = -1
        body = b""
        bad_frame = (
            content_length < 0
            or b"chunked" in te
            or content_length > _MAX_BODY
        )
        if (
            not bad_frame
            and version != b"HTTP/1.0"
            and headers.get(b"expect", b"").lower() == b"100-continue"
        ):
            # answer the interim reply like the stock handler
            # (http.server handle_expect_100): without it curl holds a
            # large /solve_batch body back for its ~1 s Expect timeout
            # before sending (ROADMAP fastserve-hardening (b)). Never
            # for HTTP/1.0 requests (RFC 7231 §5.1.1: ignore Expect
            # there — a 1.0 client would read the interim 100 as the
            # final response), matching the stock handler's version gate.
            conn.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
        if not bad_frame and content_length:
            body = rfile.read(content_length)
            if len(body) < content_length:
                return False  # client died mid-body
        if bad_frame:
            path_s = path.decode("latin-1")
            if path_s in ("/solve", "/solve_batch"):
                self._record(path_s, t0, error=True)
            self._reply(
                conn, 400, {"error": "Invalid request"}, close=True,
                request_id=req_id,
            )
            return False

        path_s = path.decode("latin-1")
        # open the request span at ingress for the traced routes; the
        # route core runs inside it (the coalescer picks the span up from
        # the thread-local at submit — obs/trace.py)
        trace = None
        if method == b"POST" and (
            path_s == "/solve"
            or (path_s == "/solve_batch" and self.expose_batch)
        ):
            trace = http_api.start_trace(self.p2p_node, path_s, req_id)
        try:
            status, payload, close_after, degraded, cached = self._route(
                method,
                path_s,
                body,
                t0,
                deadline_ms=http_api._parse_deadline_ms(
                    headers.get(b"x-deadline-ms")
                ),
            )
        except BaseException:
            # a route-core crash (the worker-pool catch-all drops the
            # connection) must still CLOSE the span: workers are reused,
            # so a leaked thread-local would attach this dead request's
            # trace to the next request on this thread — and the crashed
            # request is exactly the span an incident dump needs
            http_api.finish_trace(self.p2p_node, trace, 500)
            raise
        record = http_api.finish_trace(
            self.p2p_node, trace, status, degraded=degraded
        )
        self._reply(
            conn, status, payload, close=close or close_after,
            degraded=degraded, cached=cached,
            request_id=req_id,
            timing=http_api.timing_header_value(record)
            if record is not None and want_timing
            else None,
        )
        return not (close or close_after)

    # -- routing -----------------------------------------------------------
    def _route(
        self, method: bytes, path: str, body: bytes, t0: float,
        deadline_ms=None,
    ):
        """Returns (status, payload, close_after, degraded, cached).
        Bodies come from the shared route cores — byte-identical to the
        stock transport; ``degraded`` marks fallback-served /solve
        answers (the X-Degraded header), ``cached`` answers served from
        the canonical-form cache (the X-Cache: hit header)."""
        node = self.p2p_node
        if method == b"POST":
            if path == "/solve":
                status, payload, error, degraded, cached = (
                    http_api.solve_route(
                        node, body, deadline_ms=deadline_ms
                    )
                )
                shed = status == 429
                self._record(
                    "/solve", t0, error=error and not shed, shed=shed
                )
                return status, payload, False, degraded, cached
            if path == "/solve_batch" and self.expose_batch:
                status, payload, error, degraded, cached = (
                    http_api.solve_batch_route(
                        node, body, deadline_ms=deadline_ms
                    )
                )
                self._record("/solve_batch", t0, error=error)
                return status, payload, False, degraded, cached
            if (
                path == "/debug/flightrecord"
                and getattr(node, "flight", None) is not None
            ):
                status, payload, _error = http_api.flightrecord_route(node)
                return status, payload, False, False, False
            if path == "/debug/faults" and getattr(
                node, "chaos_routes", False
            ):
                # chaos-harness injector arming (ISSUE 14) — shared core
                status, payload, _error = http_api.faults_route(
                    node, body
                )
                return status, payload, False, False, False
            # unknown POST path: the stock handler never reads these
            # bodies and must close; this transport already consumed the
            # body, but it keeps the same observable contract
            return 404, {"error": "Invalid endpoint"}, True, False, False
        if method == b"GET":
            if path == "/stats":
                return (
                    200,
                    http_api.stats_payload(node, self.expose_serving),
                    False,
                    False,
                    False,
                )
            if path == "/network":
                return 200, node.network_view(), False, False, False
            if path == "/metrics" and self.expose_metrics:
                return (
                    200, http_api.metrics_payload(node), False, False,
                    False,
                )
            if path in http_api.PROM_PATHS and self.expose_metrics:
                # Prometheus exposition — the shared core renders it, so
                # the bytes match the stock transport's exactly
                return (
                    200, http_api.metrics_prom_payload(node), False,
                    False, False,
                )
            if path == http_api.CLUSTER_PATH and self.expose_metrics:
                # the gossip-aggregated fleet view (ISSUE 10)
                return (
                    200, http_api.cluster_payload(node), False, False,
                    False,
                )
            if path in http_api.CLUSTER_PROM_PATHS and self.expose_metrics:
                return (
                    200, http_api.cluster_prom_payload(node), False,
                    False, False,
                )
            if (
                path == "/debug/trace"
                and getattr(node, "flight", None) is not None
            ):
                # the span ring as Perfetto-loadable trace-event JSON
                status, payload, _error = http_api.trace_export_route(node)
                return status, payload, False, False, False
            if path == "/healthz":
                return (
                    200, http_api.healthz_payload(node), False, False,
                    False,
                )
            if path == "/readyz":
                status, payload = http_api.readyz_route(node)
                return status, payload, False, False, False
        return 404, {"error": "Invalid endpoint"}, False, False, False

    def _record(
        self, route: str, t0: float, error: bool = False, shed: bool = False
    ) -> None:
        http_api.record_route(self.p2p_node, route, t0, error=error, shed=shed)

    # -- response ----------------------------------------------------------
    @staticmethod
    def _reply(
        conn, status: int, payload, *, close: bool, degraded: bool = False,
        cached: bool = False, request_id=None, timing=None,
    ) -> None:
        if isinstance(payload, bytes):
            # pre-rendered non-JSON body (the Prometheus exposition)
            body = payload
            ctype = http_api.PROM_CONTENT_TYPE.encode()
        else:
            body = json.dumps(payload).encode()
            ctype = b"application/json"
        extra = b"Connection: close\r\n" if close else b""
        if degraded:
            # fallback-served answer marker; body stays byte-identical
            # (see http_api.SudokuHTTPHandler._send_response)
            extra = b"X-Degraded: true\r\n" + extra
        if cached:
            # answer-cache marker (cache/, ISSUE 13); same contract
            extra = b"X-Cache: hit\r\n" + extra
        if timing is not None:
            # the opt-in span breakdown (client sent X-Timing)
            extra = b"X-Timing: %s\r\n%s" % (timing.encode(), extra)
        if request_id is not None:
            # every response correlates (ensure_request_id sanitized it)
            extra = b"X-Request-Id: %s\r\n%s" % (request_id.encode(), extra)
        if status == 429:
            retry = http_api.retry_after_header(payload)
            if retry is not None:
                extra = b"Retry-After: %s\r\n%s" % (retry.encode(), extra)
        head = (
            b"HTTP/1.1 %d %s\r\n"
            b"Content-type: %s\r\n"
            b"Content-Length: %d\r\n"
            b"%s\r\n"
            % (
                status,
                _REASONS.get(status, b"Unknown"),
                ctype,
                len(body),
                extra,
            )
        )
        conn.sendall(head + body)
