"""Lean threaded HTTP/1.1 transport for the serving hot path.

`http.server`'s BaseHTTPRequestHandler costs ~1-2 ms of pure-Python (and
GIL-held) time per request — request-line regex, an email.parser pass over
the headers, date formatting for every response. On a CPU-fallback host
that overhead, not the device, is the serving ceiling: the coalescer
merges device work so well (parallel/coalescer.py) that the transport
becomes the bottleneck (measured ~330 puzzles/s flat with http.server vs
~2700 boards/s of warm bucket-8 device capacity on 2 cores).

This module is the matching inference-stack transport: a thread per
connection reading keep-alive requests off one buffered socket file,
parsing just the request line + the three headers that matter
(Content-Length / Transfer-Encoding / Connection), and answering from a
pre-baked header template. Route handling and response BODIES are the
exact shared cores in http_api.py (`solve_route`, `solve_batch_route`,
`stats_payload`, `metrics_payload`), so the serving surface stays
byte-identical to the reference no matter which transport carried it —
the A/B in `bench.py --mode concurrent` measures this stack against the
seed's (`--seed-serving` keeps the stock http.server + HTTP/1.0 path).

Framing rules match the stock handler's `_read_body`: a request whose
body cannot be consumed (chunked transfer, malformed/negative
Content-Length, over the size cap) answers 400 and closes — leftover
body bytes on a persistent connection would be parsed as the next
request's start line. Unknown POST paths also close, keeping the stock
handler's contract (tests/test_net_node.py keep-alive suite runs against
whichever transport `make_http_server` returns).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time

from . import http_api

logger = logging.getLogger(__name__)

_REASONS = {200: b"OK", 400: b"Bad Request", 404: b"Not Found"}
# generous cap for any route; /solve_batch's documented bound (http_api)
_MAX_BODY = http_api.MAX_BATCH_BYTES
_MAX_LINE = 65536
_MAX_HEADERS = 100


class FastHTTPServer:
    """Drop-in for ThreadingHTTPServer's lifecycle surface:
    ``serve_forever()`` blocks (run it in a thread), ``shutdown()`` stops
    the accept loop, ``server_address`` carries the bound (host, port).
    In-flight connections are daemon threads; ``shutdown`` stops new
    accepts and lets live requests finish."""

    def __init__(
        self,
        p2p_node,
        host: str,
        port: int,
        *,
        expose_metrics: bool = False,
        expose_batch: bool = False,
        expose_serving: bool = False,
    ):
        self.p2p_node = p2p_node
        self.expose_metrics = expose_metrics
        self.expose_batch = expose_batch
        self.expose_serving = expose_serving
        # deep accept queue, same rationale as the old _ThreadingHTTPServer:
        # the stock 5-deep backlog drops SYNs under a 64-client burst and
        # the overflow crawls through 1/3/7 s retransmit backoff
        self._sock = socket.create_server(
            (host, port), backlog=1024, reuse_port=False
        )
        self.server_address = self._sock.getsockname()
        self._shutdown = False

    # -- lifecycle ---------------------------------------------------------
    def serve_forever(self) -> None:
        while not self._shutdown:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                break  # listener closed by shutdown()
            threading.Thread(
                target=self._serve_connection,
                args=(conn,),
                daemon=True,
            ).start()

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._sock.close()
        except OSError:
            pass

    server_close = shutdown  # stock servers expose both

    # -- connection loop ---------------------------------------------------
    def _serve_connection(self, conn: socket.socket) -> None:
        conn.settimeout(300.0)  # reap half-dead keep-alive clients
        rfile = conn.makefile("rb", -1)
        try:
            while not self._shutdown:
                if not self._handle_one(conn, rfile):
                    break
        except (OSError, ValueError):
            pass  # client went away mid-request; nothing to answer
        finally:
            try:
                rfile.close()
            finally:
                try:
                    conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                conn.close()

    def _handle_one(self, conn, rfile) -> bool:
        """Serve one request; returns False when the connection is done."""
        line = rfile.readline(_MAX_LINE + 1)
        if not line:
            return False  # client closed cleanly between requests
        if line in (b"\r\n", b"\n"):
            return True  # tolerate a stray blank line (RFC 9112 §2.2)
        t0 = time.perf_counter()
        parts = line.split()
        if len(parts) != 3 or len(line) > _MAX_LINE:
            return False  # not HTTP; drop the connection
        method, path, version = parts
        headers = {}
        for _ in range(_MAX_HEADERS):
            h = rfile.readline(_MAX_LINE + 1)
            if h in (b"\r\n", b"\n", b""):
                break
            if len(h) > _MAX_LINE or not h.endswith(b"\n"):
                # oversize or truncated header line: readline returned a
                # fragment, and the NEXT readline would re-parse its tail
                # as a forged header (e.g. a smuggled content-length that
                # desyncs keep-alive framing) — drop the connection
                return False
            key, sep, value = h.partition(b":")
            if sep:
                headers[key.strip().lower()] = value.strip()
        else:
            return False  # header flood; drop

        close = version == b"HTTP/1.0" or (
            headers.get(b"connection", b"").lower() == b"close"
        )

        # body framing (mirrors the stock handler's _read_body contract)
        te = headers.get(b"transfer-encoding", b"").lower()
        try:
            content_length = int(headers.get(b"content-length", 0))
        except ValueError:
            content_length = -1
        body = b""
        bad_frame = (
            content_length < 0
            or b"chunked" in te
            or content_length > _MAX_BODY
        )
        if not bad_frame and content_length:
            body = rfile.read(content_length)
            if len(body) < content_length:
                return False  # client died mid-body
        if bad_frame:
            path_s = path.decode("latin-1")
            if path_s in ("/solve", "/solve_batch"):
                self._record(path_s, t0, error=True)
            self._reply(conn, 400, {"error": "Invalid request"}, close=True)
            return False

        status, payload, close_after = self._route(
            method, path.decode("latin-1"), body, t0
        )
        self._reply(conn, status, payload, close=close or close_after)
        return not (close or close_after)

    # -- routing -----------------------------------------------------------
    def _route(self, method: bytes, path: str, body: bytes, t0: float):
        """Returns (status, payload, close_after). Bodies come from the
        shared route cores — byte-identical to the stock transport."""
        node = self.p2p_node
        if method == b"POST":
            if path == "/solve":
                status, payload, error = http_api.solve_route(node, body)
                self._record("/solve", t0, error=error)
                return status, payload, False
            if path == "/solve_batch" and self.expose_batch:
                status, payload, error = http_api.solve_batch_route(
                    node, body
                )
                self._record("/solve_batch", t0, error=error)
                return status, payload, False
            # unknown POST path: the stock handler never reads these
            # bodies and must close; this transport already consumed the
            # body, but it keeps the same observable contract
            return 404, {"error": "Invalid endpoint"}, True
        if method == b"GET":
            if path == "/stats":
                return (
                    200,
                    http_api.stats_payload(node, self.expose_serving),
                    False,
                )
            if path == "/network":
                return 200, node.network_view(), False
            if path == "/metrics" and self.expose_metrics:
                return 200, http_api.metrics_payload(node), False
        return 404, {"error": "Invalid endpoint"}, False

    def _record(self, route: str, t0: float, error: bool = False) -> None:
        m = getattr(self.p2p_node, "metrics", None)
        if m is not None:
            m.record(route, time.perf_counter() - t0, error=error)

    # -- response ----------------------------------------------------------
    @staticmethod
    def _reply(conn, status: int, payload, *, close: bool) -> None:
        body = json.dumps(payload).encode()
        head = (
            b"HTTP/1.1 %d %s\r\n"
            b"Content-type: application/json\r\n"
            b"Content-Length: %d\r\n"
            b"%s\r\n"
            % (
                status,
                _REASONS.get(status, b"Unknown"),
                len(body),
                b"Connection: close\r\n" if close else b"",
            )
        )
        conn.sendall(head + body)
