"""HTTP API: POST /solve, GET /stats, GET /network — byte-identical bodies.

Response contract (reference node.py:661-704):
  POST /solve  200 → the solved grid as a JSON array-of-arrays;
               400 → {"error": "No solution found", "solution": null}
  GET  /stats  200 → the merged all_stats shape
  GET  /network 200 → the all_peers dict, or {self_id: []} when alone
  anything else 404 → {"error": "Invalid endpoint"}

Fixes behind the surface: a *threading* HTTP server, so /stats and /network
answer while a /solve is in flight (the reference's single-threaded server
blocks them — SURVEY.md §1 [verified live]); malformed /solve bodies get a
400 JSON error instead of the reference's uncaught exception + empty reply
(SURVEY.md §2 HTTP row [verified live]).
"""

from __future__ import annotations

import json
import logging
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# the exposition content type is defined by the renderer — ONE site
from ..obs.prom import CONTENT_TYPE as PROM_CONTENT_TYPE
from ..obs.trace import current_trace, new_request_id, valid_request_id

logger = logging.getLogger(__name__)


MAX_BATCH = 4096        # board-count guard for /solve_batch
MAX_BATCH_BYTES = 32 << 20  # body-size guard, checked before buffering
# largest /solve_batch the answer cache consults/feeds (ISSUE 13): the
# per-board canonicalization (~0.3-0.5 ms pure Python) is a rounding
# error on a viral single request but ~2 s of serial handler-thread
# work on a MAX_BATCH bulk job — and bulk batches are offline
# throughput traffic, not the duplicated request stream the cache
# exists for. Larger batches skip the cache entirely (lookup AND
# store) and behave exactly as the pre-cache path.
CACHE_BATCH_MAX = 256


def _board_error(sudoku, size: int) -> str | None:
    """Semantic body validation: reject JSON-valid-but-malformed boards
    before they reach the engine (VERDICT r4 task 2). The reference crashes
    uncaught on these — `board[row][col]` on a string, a ragged grid, or a
    non-9×9 grid raises in the handler thread and the client gets an empty
    reply (reference node.py:672-690 [verified live]). Returns a reason
    string when invalid, None when the board is a clean ``size``×``size``
    grid of ints in 0..size."""
    if not isinstance(sudoku, list) or len(sudoku) != size:
        return f"board must be a {size}x{size} array"
    for row in sudoku:
        if not isinstance(row, list) or len(row) != size:
            return f"board must be a {size}x{size} array"
        for v in row:
            if type(v) is not int or not 0 <= v <= size:
                return f"cells must be integers in 0..{size}"
    return None


# -- route cores -------------------------------------------------------------
# Shared by the stock handler below (the seed's transport, kept for
# --seed-serving A/B runs) and the lean serving transport (fastserve.py):
# each takes the already-framed request body and returns
# (status, payload, error_flag) so response bodies stay byte-identical no
# matter which transport carried the request.


def record_route(
    p2p_node, route: str, t0: float, error: bool = False, shed: bool = False
) -> None:
    """Fold one request into the node's RequestMetrics (when attached) —
    the single definition both transports call (ROADMAP
    fastserve-hardening (c); the stock handler and fastserve used to
    carry byte-identical private copies)."""
    m = getattr(p2p_node, "metrics", None)
    if m is not None:
        m.record(route, time.perf_counter() - t0, error=error, shed=shed)


def ensure_request_id(raw) -> str:
    """The response's ``X-Request-Id``: the client's own id when it sent
    a well-formed one (so retries across replicas correlate), else a
    fresh 16-hex id. Every response on both transports carries it —
    including 404s, 429 sheds, and degraded answers — because the replies
    that went WRONG are exactly the ones an operator needs to find again
    in the flight record."""
    return valid_request_id(raw) or new_request_id()


def start_trace(p2p_node, route: str, request_id: str):
    """Open a request-lifecycle span (obs/trace.py) when the node carries
    a tracer; None otherwise — both transports call this unconditionally
    at ingress for the traced routes (/solve, /solve_batch)."""
    tracer = getattr(p2p_node, "tracer", None)
    if tracer is None:
        return None
    return tracer.start(route, trace_id=request_id)


def finish_trace(p2p_node, trace, status: int, degraded: bool = False):
    """Close a span; returns the finished record (the ``X-Timing`` header
    source) or None. Tolerates trace=None so call sites stay branch-free."""
    if trace is None:
        return None
    tracer = getattr(p2p_node, "tracer", None)
    if tracer is None:
        return None
    return tracer.finish(trace, status, degraded=degraded)


def timing_header_value(record: dict) -> str:
    """The opt-in ``X-Timing`` response header (sent when the request
    carried an ``X-Timing`` header): the span's stage breakdown as
    compact JSON — where this request's milliseconds went."""
    return json.dumps(
        {
            "total_ms": record["total_ms"],
            # front-door answer-cache consult (ISSUE 13): canonicalize +
            # lookup (+ peer fetch wait) — nonzero on hits AND misses
            "cache_ms": record["cache_ms"],
            "queue_ms": record["queue_ms"],
            "coalesce_ms": record["coalesce_ms"],
            "device_ms": record["device_ms"],
            "verify_ms": record["verify_ms"],
            "fallback_ms": record["fallback_ms"],
            "bucket": record["bucket"],
            "batch_id": record["batch_id"],
            "degraded": record["degraded"],
            "fallback": record["fallback"],
            "farmed": record["farmed"],
            # continuous batching (ISSUE 12): how many device segments
            # this request's device span covered (0 on the closed loop)
            "segments": record["segments"],
        },
        separators=(",", ":"),
    )


def _parse_deadline_ms(raw):
    """``X-Deadline-Ms`` header → float ms (relative latency budget), or
    None when absent/garbage. Garbage is treated as no header rather than
    a 400: the header is advisory and must never break a client that
    would have succeeded without it. A non-positive value is meaningful —
    it is already expired at arrival and sheds immediately
    (serving/admission.py)."""
    if raw is None:
        return None
    if isinstance(raw, bytes):
        raw = raw.decode("latin-1", "replace")
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


def _shed_payload(error: str, retry_after_s) -> dict:
    """The 429 body shape (admission shed / expired deadline). Carries the
    retry hint in ms so transports can derive the Retry-After header
    (integer seconds) from the payload without a side channel."""
    return {
        "error": error,
        "retry_after_ms": round(max(0.0, retry_after_s or 0.0) * 1e3, 1),
    }


def retry_after_header(payload) -> str | None:
    """Retry-After header value (integer seconds, floor 1) for a 429
    payload built by ``_shed_payload``; None for anything else."""
    if isinstance(payload, dict) and "retry_after_ms" in payload:
        return str(max(1, -(-int(payload["retry_after_ms"]) // 1000)))
    return None


def _parse_board(p2p_node, body: bytes):
    """Parse + semantically validate a /solve body. Returns the board
    list, or None after logging — the shared early step the cache path
    and the engine core both use (parsed once per request)."""
    try:
        sudoku = json.loads(body.decode("utf-8"))["sudoku"]
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        # TypeError: a JSON-valid non-object body ([1,2,3], "foo") makes
        # body["sudoku"] a non-subscript access — same 400, never a dead
        # handler thread (code-review r5)
        return None
    reason = _board_error(sudoku, p2p_node.engine.spec.size)
    if reason is not None:
        logger.info("rejected /solve body: %s", reason)
        return None
    return sudoku


def _cache_lookup(p2p_node, sudoku, deadline_ms=None):
    """Front-door cache consult (cache/, ISSUE 13): local lookup, then —
    on a miss for a key some fresh peer's hot-set gossip advertises — a
    bounded peer fetch (verified on arrival) before any dispatch.
    Returns (answer | None, canonical form | None); the elapsed time is
    stamped as the request span's ``cache`` stage either way, so misses'
    canonicalization cost is as visible as hits' savings.

    ``deadline_ms`` (the request's relative budget) clamps the peer
    fetch wait: a request never parks past its own deadline for an
    answer it could no longer use."""
    cache = p2p_node.answer_cache
    t0 = time.monotonic()
    try:
        # miss accounting deferred (count_miss=False): the peer-fetch
        # path probes the store twice for one request, and exactly one
        # outcome — hit or miss — may land in the counters (a
        # peer-served request double-counting as miss AND hit would
        # corrupt hit_rate_pct and the fleet rollup)
        answer, form = cache.lookup(sudoku, count_miss=False)
        if answer is None and form is not None:
            gossip = getattr(p2p_node, "cache_gossip", None)
            if gossip is not None:
                budget_s = None
                if deadline_ms is not None:
                    budget_s = (
                        deadline_ms / 1e3 - (time.monotonic() - t0)
                    )
                if gossip.try_peer_fetch(form.key, timeout_s=budget_s):
                    # a verified peer answer just landed under this
                    # key: re-run the lookup and serve it as a hit
                    answer, form = cache.lookup(
                        sudoku, form, count_miss=False
                    )
        if answer is None and form is not None:
            cache._count("misses")
    finally:
        tr = current_trace()
        if tr is not None:
            tr.mark("cache", time.monotonic() - t0)
    return answer, form


def solve_route(p2p_node, body: bytes, deadline_ms=None):
    """POST /solve: the reference's solve surface (node.py:661-690).

    Returns ``(status, payload, error_flag, degraded, cached)`` —
    ``degraded`` True when the answer came from the supervisor's
    host-oracle fallback (serving/health.py); ``cached`` True when it
    came from the canonical-form answer cache (cache/, ISSUE 13).
    Transports surface them as the ``X-Degraded`` / ``X-Cache: hit``
    response headers, keeping the BODY byte-identical to the reference.

    With a cache attached, the lookup runs BEFORE admission accounting:
    a hit never enters the pending budget, never feeds the completion-
    rate estimator (a hot-set storm answering in microseconds must not
    inflate projected device capacity — the PR 2 malformed-body failure
    shape), and is counted in the separate ``admission.cache_hits``
    gauge instead.

    ``deadline_ms`` is the request's relative latency budget (the
    ``X-Deadline-Ms`` header, parsed by the transport). With an admission
    controller attached to the node (serving/admission.py; off by
    default), overload answers ``429`` here — shed at arrival when the
    projected queue wait already exceeds the budget or the pending
    capacity is full, or after the fact when the request expired waiting
    in the coalescer queue. Without one, behavior is byte-identical to
    the pre-admission stack (the header is ignored).
    """
    adm = getattr(p2p_node, "admission", None)
    cache = getattr(p2p_node, "answer_cache", None)
    sudoku = None
    form = None
    already_expired = (
        deadline_ms is not None and deadline_ms <= 0
    )
    if cache is not None and not already_expired:
        # (an already-expired budget skips the consult entirely — the
        # admission layer's microsecond 429 is the cheapest answer a
        # dead-on-arrival request can get)
        t_arrival = time.monotonic()
        sudoku = _parse_board(p2p_node, body)
        if sudoku is None:
            if adm is not None:
                # parsed (and failed) before try_admit ran: keep the
                # malformed-body flood visible to admission's arrival
                # rate + rejected counter — pre-cache it was admitted
                # then released served=False, and an operator's
                # dashboard must not read an active flood as a quiet
                # healthy node
                adm.note_rejected()
            return 400, {"error": "Invalid request"}, True, False, False
        answer, form = _cache_lookup(
            p2p_node, sudoku, deadline_ms=deadline_ms
        )
        if answer is not None:
            if adm is not None:
                adm.note_cache_hit()
            return 200, answer, False, False, True
        if deadline_ms is not None:
            # the consult (canonicalize + lookup, possibly a bounded
            # peer-fetch wait) happened before admission: charge it
            # against the client's budget — the deadline measures the
            # client's wait, not where the server spent it. A budget
            # the consult already exhausted sheds at try_admit
            # (non-positive = expired at arrival)
            deadline_ms -= (time.monotonic() - t_arrival) * 1e3
    if adm is None:
        return _solve_core(
            p2p_node, body, None, sudoku=sudoku, form=form
        )
    decision = adm.try_admit(deadline_ms)
    if not decision.admitted:
        logger.debug("shed /solve at arrival (%s)", decision.reason)
        return (
            429,
            _shed_payload("Overloaded", decision.retry_after_s),
            True,
            False,
            False,
        )
    from ..serving.admission import DeadlineExceeded

    expired = False
    outcome = {"served": False}
    try:
        return _solve_core(
            p2p_node, body, decision.deadline_s, outcome,
            sudoku=sudoku, form=form,
        )
    except DeadlineExceeded:
        # admitted in time, overtaken by load: dropped at batch formation
        # (parallel/coalescer.py) — the device never ran it
        expired = True
        return (
            429,
            _shed_payload("Deadline exceeded", adm.retry_hint_s()),
            True,
            False,
            False,
        )
    finally:
        # served=False (a body rejected before the engine ran) must not
        # feed the completion-rate estimator: a malformed-body flood
        # would otherwise read as huge capacity and disable the
        # projected-wait shed exactly when real traffic needs it
        adm.release(expired=expired, served=outcome["served"])


def _solve_core(
    p2p_node, body: bytes, deadline_s, outcome=None, *,
    sudoku=None, form=None,
):
    # debug, not info: two formatted log records per request is measurable
    # GIL time under a 64-client closed loop (the reference logs every
    # request at INFO, but its serving path was never multi-tenant);
    # error paths still log at info
    t_in = time.time()
    logger.debug("received /solve POST request")
    if sudoku is None:
        # no cache consult happened upstream: parse here (once)
        sudoku = _parse_board(p2p_node, body)
        if sudoku is None:
            return 400, {"error": "Invalid request"}, True, False, False
    if outcome is not None:
        outcome["served"] = True  # past validation: the engine runs now
    from ..models.oracle import OracleBudgetExceeded

    try:
        solution, info = p2p_node.peer_sudoku_solve_info(
            sudoku, deadline_s=deadline_s
        )
    except OracleBudgetExceeded:
        # degraded-mode serving hit its host-oracle time budget
        # (serving/health.py fallback_budget_s, ISSUE 8): the node is in
        # fallback AND this board's host solve is adversarial-deep —
        # answer a clean 503 instead of pinning a bounded transport
        # worker on an exponential MRV tail. 503, not 429: the client
        # did nothing wrong and the node is not overloaded — it is
        # temporarily unable to serve THIS class of request correctly.
        logger.warning("503: degraded and over the fallback budget")
        return (
            503,
            {"error": "Degraded: fallback budget exceeded"},
            True,
            True,
            False,
        )
    degraded = bool(info.get("degraded"))
    logger.debug("execution time: %s", time.time() - t_in)
    if solution:
        cache = getattr(p2p_node, "answer_cache", None)
        if cache is not None:
            # write gate: store() re-verifies host-side (clue match +
            # rule check) before admission — whatever path answered
            # (device, fallback, farm), a wrong answer cannot enter
            # (cache/store.py). The canonical form from the lookup is
            # reused so the reduction is paid once per request.
            cache.store(sudoku, solution, form)
        return 200, solution, False, degraded, False
    return (
        400,
        {"error": "No solution found", "solution": solution},
        True,
        degraded,
        False,
    )


def solve_batch_route(p2p_node, body: bytes, deadline_ms=None):
    """POST /solve_batch (opt-in extension, not a reference surface): the
    engine's bucketed batch path over HTTP — the framework's headline
    strength (bench.py throughput) reachable by a serving client, instead
    of one board per request. Body: {"sudokus": [grid, ...]} →
    {"solutions": [grid|null, ...], "solved": n, "capped": n}. null rows
    mean not solved; capped counts rows whose search exhausted the
    iteration budget (not finished ≠ proven unsatisfiable, engine.py).

    Returns ``(status, payload, error_flag, degraded, cached)`` like
    ``solve_route`` (ISSUE 12 satellite — the PR 5 known limit closed):
    under an open breaker or a mid-batch device failure the supervised
    engine answers every board from the host-oracle fallback; the reply
    then carries per-board ``degraded`` flags in the body and transports
    surface the any-board summary as ``X-Degraded``, instead of the
    whole batch erroring.

    With an answer cache attached (cache/, ISSUE 13), cached boards
    STRIP OUT of the batch before coalescing — only the misses pay
    admission into the engine's batch path — and their answers merge
    back in request order. ``cached`` is the any-board summary (the
    ``X-Cache: hit`` header); the body shape is unchanged.

    ``deadline_ms`` is the request's relative latency budget (the
    ``X-Deadline-Ms`` header, parsed by the transport — the batch
    shape's deadline leg of the dispatch contract, analysis/seams.py):
    a budget already expired at arrival, or exhausted by validation and
    the cache consult, sheds 429 BEFORE the engine dispatch — the
    device never runs a batch nobody is waiting for. An all-hit batch
    never sheds: the answers are already in hand. Without the header,
    behavior is unchanged."""
    t_arrival = time.monotonic()
    try:
        sudokus = json.loads(body.decode())["sudokus"]
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return 400, {"error": "Invalid request"}, True, False, False
    size = p2p_node.engine.spec.size
    if not isinstance(sudokus, list) or not 1 <= len(sudokus) <= MAX_BATCH:
        reason = f"need 1..{MAX_BATCH} boards"
    else:
        reason = next(
            filter(None, (_board_error(s, size) for s in sudokus)), None
        )
    if reason is not None:
        logger.info("rejected /solve_batch body: %s", reason)
        return 400, {"error": "Invalid request"}, True, False, False
    cache = getattr(p2p_node, "answer_cache", None)
    n = len(sudokus)
    if cache is not None and n > CACHE_BATCH_MAX:
        # oversized bulk jobs skip the consult (and the symmetric store
        # cost below): serial canonicalization of thousands of boards
        # on the handler thread is a latency regression the
        # duplicated-request stream this cache serves can never repay
        # there. Small batches still consult AND warm the cache.
        cache = None
    answers = [None] * n
    forms = [None] * n
    hit = [False] * n
    if cache is not None:
        t0 = time.monotonic()
        for i, s in enumerate(sudokus):
            answers[i], forms[i] = cache.lookup(s)
            hit[i] = answers[i] is not None
        tr = current_trace()
        if tr is not None:
            tr.mark("cache", time.monotonic() - t0)
    miss_idx = [i for i in range(n) if not hit[i]]
    degraded = False
    degraded_rows = [False] * n
    capped = 0
    solved = n - len(miss_idx)
    if miss_idx:
        if deadline_ms is not None:
            # pre-dispatch expiry check (the contract's deadline leg):
            # validation + the cache consult are charged against the
            # client's budget, and a batch whose budget they exhausted
            # sheds here — mid-batch the chunks run to completion (a
            # batch is one dispatch unit; per-chunk abandonment would
            # waste the device work already queued)
            remaining_ms = (
                deadline_ms - (time.monotonic() - t_arrival) * 1e3
            )
            if remaining_ms <= 0:
                adm = getattr(p2p_node, "admission", None)
                retry = (
                    adm.retry_hint_s() if adm is not None else None
                )
                logger.debug("shed /solve_batch: deadline expired")
                return (
                    429,
                    _shed_payload("Deadline exceeded", retry),
                    True,
                    False,
                    False,
                )
        solutions, mask, info = p2p_node.batch_sudoku_solve(
            [sudokus[i] for i in miss_idx]
        )
        capped = info["capped"]
        solved += int(mask.sum())
        degraded = bool(info.get("degraded"))
        for pos, i in enumerate(miss_idx):
            if mask[pos]:
                answers[i] = solutions[pos].tolist()
                if cache is not None:
                    # write-gated like every other path (store verifies
                    # host-side); the lookup's form is reused
                    cache.store(sudokus[i], answers[i], forms[i])
            if degraded:
                degraded_rows[i] = bool(info["degraded_boards"][pos])
    payload = {
        "solutions": answers,
        "solved": solved,
        "capped": capped,
    }
    if degraded:
        # per-board flags only when fallback serving actually happened:
        # the healthy-path body stays byte-identical to the pre-PR12 one
        # (cache-stripped boards read False — a cached answer was
        # verified at write time, never a fallback product)
        payload["degraded"] = degraded_rows
    return 200, payload, False, degraded, any(hit)


def healthz_payload(p2p_node):
    """GET /healthz — liveness. 200 the moment the HTTP plane answers:
    a live process that is DEGRADED or even LOST must NOT be restarted
    by its orchestrator (it is still answering correctly from the
    fallback); that distinction is exactly what /readyz carries."""
    return {"ok": True}


def readyz_route(p2p_node):
    """GET /readyz — readiness, returns (status, payload): 200 when this
    node should receive traffic (engine tier-0 ``warmed`` AND the
    supervisor — when one is attached — is not LOST), else 503 so an
    orchestrator gates traffic away while the node cold-starts or
    rebuilds a lost engine. DEGRADED stays ready on purpose: the
    host-oracle fallback serves correct answers, and pulling the node
    would turn a slow-but-correct replica into lost capacity.

    Both transports serve this byte-identically (shared core, like every
    other route); unlike /metrics these two routes are unconditional —
    an orchestrator's probe config cannot depend on app flags.
    """
    eng = getattr(p2p_node, "engine", None)
    warmed = bool(getattr(eng, "warmed", False))
    sup = getattr(eng, "supervisor", None)
    lost = bool(sup is not None and sup.is_lost)
    # ONE readiness predicate (engine.ready — shared with the telemetry
    # digest and the autopilot's join gate); the body fields stay the
    # PR 5 shape byte-for-byte. Duck-typed engines without ready() keep
    # the full old predicate — warmed AND not lost, never warmed alone
    ready = bool(eng is not None and eng.ready()) if (
        hasattr(eng, "ready")
    ) else (warmed and not lost)
    body = {"ready": ready, "warmed": warmed}
    if sup is not None:
        body["health"] = sup.state
    return (200 if ready else 503), body


def stats_payload(p2p_node, expose_serving: bool):
    """GET /stats: the merged all_stats shape; the serving block
    (coalescer counters, net/stats.serving_snapshot) is an extension key
    next to the reference's "all"/"nodes", only when the operator asked
    for it."""
    body = p2p_node.get_stats()
    if expose_serving:
        from .stats import serving_snapshot

        eng = getattr(p2p_node, "engine", None)
        if eng is not None:
            body["serving"] = serving_snapshot(eng)
    return body


def metrics_payload(p2p_node):
    """GET /metrics (opt-in): per-route percentiles plus engine health
    (frontier fallbacks / serving-loop liveness) and membership churn
    machinery — route keys all start with "/", so the extra keys can't
    collide."""
    m = getattr(p2p_node, "metrics", None)
    body = m.summary() if m is not None else {}
    eng = getattr(p2p_node, "engine", None)
    if eng is not None and hasattr(eng, "health"):
        body["engine"] = eng.health()
    answer_cache = getattr(p2p_node, "answer_cache", None)
    if answer_cache is not None and isinstance(
        body.get("engine", {}).get("cost"), dict
    ):
        # the canonical-form answer cache's counters (cache/, ISSUE 13)
        # live under engine.cost: cache hits ARE device cost avoided,
        # and the cost block is where an operator reads serving spend
        snap = answer_cache.snapshot()
        gossip = getattr(p2p_node, "cache_gossip", None)
        if gossip is not None:
            snap["gossip"] = gossip.snapshot()
        body["engine"]["cost"]["cache"] = snap
    m_health = getattr(
        getattr(p2p_node, "membership", None), "health", None
    )
    if m_health is not None:
        body["membership"] = m_health()
    adm = getattr(p2p_node, "admission", None)
    if adm is not None:
        # the overload control plane's view: shed/expired counters, queue
        # depth, EWMA arrival/completion rates, projected wait
        # (serving/admission.py); the coalescer's expired counter and —
        # in adaptive mode — the current max-wait ride under
        # "engine"/"coalescer" above
        body["admission"] = adm.snapshot()
    sup = getattr(eng, "supervisor", None)
    if sup is not None:
        # the failure-domain supervision plane (serving/health.py):
        # state machine, breaker, quarantine, fallback/probe counters —
        # plus the gossip-carried view of PEER supervisor states the
        # task farm routes around (net/stats.PeerHealth)
        health = sup.snapshot()
        peers = getattr(p2p_node, "peer_health", None)
        if peers is not None:
            health["peers"] = peers.snapshot()
        body["health"] = health
    # armed chaos injectors (utils/faults.py): their counters belong on
    # the observability surface — a chaos run must be readable from
    # /metrics, not from log scraping
    faults = {}
    wire_inj = getattr(p2p_node, "fault_injector", None)
    if wire_inj is not None:
        faults["wire"] = wire_inj.counts()
    eng_inj = getattr(eng, "fault_injector", None)
    if eng_inj is not None:
        faults["engine"] = eng_inj.counts()
    if faults:
        body["faults"] = faults
    # the request-lifecycle tracing plane (obs/): span counters + per-
    # stage latency summaries, and the flight recorder's ring state
    tracer = getattr(p2p_node, "tracer", None)
    if tracer is not None:
        body["obs"] = tracer.snapshot()
    flight = getattr(p2p_node, "flight", None)
    if flight is not None:
        body.setdefault("obs", {})["flight"] = flight.stats()
    # the SLO burn-rate engine (obs/slo.py, ISSUE 10): per-objective
    # multi-window burn rates + fast-burn gauges; a scrape gets a fresh
    # evaluation (tick is rate-limited internally)
    slo = getattr(p2p_node, "slo", None)
    if slo is not None:
        body["slo"] = slo.snapshot()
    # the fleet autopilot (serving/autopilot.py, ISSUE 14): every
    # control loop's enable flag, knobs, and counters — burn-aware
    # admission tightening, farm ranking, hedge fired/won/budget,
    # join deferral + prewarm. Scalar leaves only, so the prom
    # exposition flattens it byte-identically on both transports.
    autopilot = getattr(p2p_node, "autopilot", None)
    if autopilot is not None:
        body["autopilot"] = autopilot.snapshot()
    return body


# the two Prometheus spellings of the /metrics surface, matched EXACTLY
# (no general query parsing: every other route's unknown-path 404 surface
# stays byte-identical to the reference)
PROM_PATHS = ("/metrics.prom", "/metrics?format=prom")

# the cluster view's spellings (ISSUE 10), same exact-match contract
CLUSTER_PATH = "/metrics/cluster"
CLUSTER_PROM_PATHS = (
    "/metrics/cluster.prom",
    "/metrics/cluster?format=prom",
)


def cluster_payload(p2p_node) -> dict:
    """``GET /metrics/cluster``: the gossip-aggregated fleet view — this
    node's own telemetry digest, every unexpired peer digest (TTL'd,
    freshness-marked), and fleet rollups (obs/cluster.py). Served by both
    transports through this one core, gated like /metrics."""
    from ..obs.cluster import cluster_snapshot

    return cluster_snapshot(p2p_node)


def cluster_prom_payload(p2p_node) -> bytes:
    """The Prometheus rendering of the SAME cluster snapshot: per-node
    gauges labeled ``{node="host:port"}`` plus flattened fleet rollups —
    one scrape config covers the whole fleet through any member."""
    from ..obs.cluster import cluster_snapshot, render_cluster_prom

    return render_cluster_prom(cluster_snapshot(p2p_node)).encode()


def trace_export_route(p2p_node):
    """``GET /debug/trace``: the flight-recorder span ring assembled as
    Chrome trace-event JSON (obs/export.py — Perfetto-loadable), request
    spans and wire-propagated farm-task spans in one tree. Returns
    (status, payload, error); 404 on nodes without a recorder, exactly
    like /debug/flightrecord."""
    flight = getattr(p2p_node, "flight", None)
    if flight is None:
        return 404, {"error": "Invalid endpoint"}, True
    from ..obs.export import build_trace

    return 200, build_trace(flight.spans()), False


def metrics_prom_payload(p2p_node) -> bytes:
    """``GET /metrics.prom`` / ``GET /metrics?format=prom``: the SAME
    dict the JSON body serializes, rendered as Prometheus text
    (obs/prom.py) plus the tracer's stage histograms as real histogram
    families. One shared core → byte-identical on both transports."""
    from ..obs.prom import render

    body = metrics_payload(p2p_node)
    tracer = getattr(p2p_node, "tracer", None)
    histograms = tracer.stages.histograms() if tracer is not None else None
    return render(body, histograms).encode()


def flightrecord_route(p2p_node):
    """POST /debug/flightrecord: operator-triggered flight-recorder dump
    (obs/flight.py — the same black box the breaker-trip/shed-storm/
    SIGUSR2 triggers write). Returns (status, payload, error): a summary
    plus the dump path when the recorder has a dump dir, else the whole
    record inline (a dir-less node still answers the incident question).
    404 on nodes without a recorder — the route does not exist there,
    exactly like the other opt-in surfaces."""
    flight = getattr(p2p_node, "flight", None)
    if flight is None:
        return 404, {"error": "Invalid endpoint"}, True
    out = flight.dump(reason="http")
    body = {
        "dumped": True,
        "reason": out["reason"],
        "seq": out["seq"],
        "path": out["path"],
        "spans": out["spans"],
        "events": out["events"],
    }
    if out["path"] is None:
        body["record"] = out["payload"]
    return 200, body, False


def faults_route(p2p_node, body: bytes):
    """POST /debug/faults (opt-in, CLI ``--chaos-injector``): arm the
    PR 5 engine-seam fault injector on a LIVE node, so a chaos harness
    (bench.py --mode chaos) can poison/slow/fail a fleet member's
    device path mid-run over HTTP instead of needing in-process access.
    Body: a JSON object with any of ``fail_next`` (int), ``delay_s``
    (float), ``poison_bucket`` (int width), ``clear`` (bool — disarm
    everything, applied FIRST so {"clear":true,"delay_s":x} re-arms
    atomically). Returns (status, payload, error) with the injector's
    counters, which also live under the ``faults`` /metrics block.

    404 on nodes without the flag — the route does not exist there,
    exactly like the other opt-in debug surfaces; values are bounded at
    the boundary (a hostile caller on the debug port can waste the
    node's time, which is what the flag opts into, but must not be able
    to crash the route)."""
    inj = getattr(
        getattr(p2p_node, "engine", None), "fault_injector", None
    )
    if inj is None or not getattr(p2p_node, "chaos_routes", False):
        return 404, {"error": "Invalid endpoint"}, True
    try:
        cmd = json.loads(body.decode("utf-8")) if body else {}
    except (ValueError, UnicodeDecodeError):
        return 400, {"error": "Invalid request"}, True
    if not isinstance(cmd, dict):
        return 400, {"error": "Invalid request"}, True
    try:
        if cmd.get("clear"):
            inj.clear()
        if "fail_next" in cmd:
            inj.arm_fail_next(max(0, min(1_000_000, int(cmd["fail_next"]))))
        if "delay_s" in cmd:
            inj.set_delay(max(0.0, min(3600.0, float(cmd["delay_s"]))))
        if "poison_bucket" in cmd:
            inj.poison_bucket(int(cmd["poison_bucket"]))
    except (TypeError, ValueError):
        return 400, {"error": "Invalid request"}, True
    return 200, {"ok": True, "counts": inj.counts()}, False


class SudokuHTTPHandler(BaseHTTPRequestHandler):
    # The stock http.server handler. The default serving transport is now
    # net/fastserve.py (same route cores, ~an order of magnitude less
    # pure-Python per request); this class carries the seed's transport
    # for --seed-serving A/B runs (make_http_server pins it to HTTP/1.0
    # there: a connection per request, exactly the seed's per-request
    # cost). Kept HTTP/1.1-capable — keep-alive needs the Content-Length
    # header _send_response sets; response bodies are byte-identical to
    # the reference either way.
    protocol_version = "HTTP/1.1"
    p2p_node = None       # set by make_http_server
    expose_metrics = False  # opt-in /metrics route (CLI --metrics); default
    #                         off keeps the 404 surface byte-identical
    expose_batch = False    # opt-in POST /solve_batch (CLI --batch-api):
    #                         the engine's bucketed batch path through HTTP
    expose_serving = False  # opt-in "serving" block on GET /stats (CLI
    #                         --serving-stats): coalescer batch-fill /
    #                         queue-depth / wait-time counters; off keeps
    #                         the reference {"all","nodes"} body exact
    MAX_BATCH = MAX_BATCH
    MAX_BATCH_BYTES = MAX_BATCH_BYTES
    _req_id = None          # per-request id, set by _begin_request
    _want_timing = False    # client sent X-Timing: opt into the breakdown

    def _begin_request(self) -> None:
        """Per-request observability context (ISSUE 6): echo or mint the
        X-Request-Id every response carries, and note whether the client
        opted into the X-Timing stage breakdown."""
        self._req_id = ensure_request_id(self.headers.get("X-Request-Id"))
        self._want_timing = self.headers.get("X-Timing") is not None

    def _send_response(
        self,
        content,
        status: int = 200,
        degraded: bool = False,
        timing=None,
        cached: bool = False,
    ) -> None:
        if isinstance(content, bytes):
            # pre-rendered non-JSON body (the Prometheus exposition)
            body = content
            ctype = PROM_CONTENT_TYPE
        else:
            body = json.dumps(content).encode()
            ctype = "application/json"
        self.send_response(status)
        self.send_header("Content-type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if self._req_id is not None:
            self.send_header("X-Request-Id", self._req_id)
        if timing is not None:
            self.send_header("X-Timing", timing)
        if degraded:
            # the degraded-serving marker (serving/health.py): a header,
            # not a body key — the body stays byte-identical to the
            # reference while clients/operators can still see the answer
            # came from the host-oracle fallback
            self.send_header("X-Degraded", "true")
        if cached:
            # the answer-cache marker (cache/, ISSUE 13): same
            # header-not-body contract — the solution grid is
            # byte-identical whether it came from the device or the
            # canonical-form cache, and that identity is the A/B
            # acceptance (bench.py --mode cache)
            self.send_header("X-Cache", "hit")
        if status == 429:
            retry = retry_after_header(content)
            if retry is not None:
                self.send_header("Retry-After", retry)
        if self.close_connection:
            # a handler that bailed without consuming the request body sets
            # close_connection (leftover bytes would desync keep-alive
            # framing); tell the client so it reconnects instead of
            # reusing a connection the server is about to drop
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)

    def _record(
        self, route: str, t0: float, error: bool = False, shed: bool = False
    ) -> None:
        record_route(self.p2p_node, route, t0, error=error, shed=shed)

    def _read_body(self, route: str, t0: float, max_bytes=None):
        """Read the request body with keep-alive-safe framing. Returns the
        bytes, or None after answering 400 — closing the connection when
        the body could NOT be consumed (chunked transfer, malformed or
        negative Content-Length, over ``max_bytes``): leftover body bytes
        on a persistent connection would be parsed as the next request's
        start line. Harmless on HTTP/1.0 (every reply closes), load-bearing
        since the switch to HTTP/1.1."""
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        try:
            content_length = int(self.headers.get("Content-Length", 0))
        except (ValueError, TypeError):
            content_length = -1
        if (
            content_length < 0
            or "chunked" in te
            or (max_bytes is not None and content_length > max_bytes)
        ):
            self.close_connection = True
            self._record(route, t0, error=True)
            self._send_response({"error": "Invalid request"}, 400)
            return None
        return self.rfile.read(content_length)

    def do_POST(self):
        t0 = time.perf_counter()
        self._begin_request()
        if self.path == "/solve":
            post_data = self._read_body("/solve", t0)
            if post_data is None:
                return
            trace = start_trace(self.p2p_node, "/solve", self._req_id)
            try:
                status, payload, error, degraded, cached = solve_route(
                    self.p2p_node, post_data,
                    deadline_ms=_parse_deadline_ms(
                        self.headers.get("X-Deadline-Ms")
                    ),
                )
            except BaseException:
                # same contract as the lean transport: a route-core crash
                # must still close the span — the crashed request is
                # exactly the span an incident dump needs
                finish_trace(self.p2p_node, trace, 500)
                raise
            record = finish_trace(
                self.p2p_node, trace, status, degraded=degraded
            )
            # record before replying: a client may poll /metrics the
            # instant its response arrives
            shed = status == 429
            self._record("/solve", t0, error=error and not shed, shed=shed)
            self._send_response(
                payload, status, degraded=degraded, cached=cached,
                timing=timing_header_value(record)
                if record is not None and self._want_timing
                else None,
            )
        elif self.path == "/solve_batch" and self.expose_batch:
            post_data = self._read_body(
                "/solve_batch", t0, max_bytes=self.MAX_BATCH_BYTES
            )
            if post_data is None:
                return
            trace = start_trace(
                self.p2p_node, "/solve_batch", self._req_id
            )
            try:
                status, payload, error, degraded, cached = (
                    solve_batch_route(
                        self.p2p_node, post_data,
                        deadline_ms=_parse_deadline_ms(
                            self.headers.get("X-Deadline-Ms")
                        ),
                    )
                )
            except BaseException:
                finish_trace(self.p2p_node, trace, 500)
                raise
            record = finish_trace(
                self.p2p_node, trace, status, degraded=degraded
            )
            self._record("/solve_batch", t0, error=error)
            self._send_response(
                payload, status, degraded=degraded, cached=cached,
                timing=timing_header_value(record)
                if record is not None and self._want_timing
                else None,
            )
        elif (
            self.path == "/debug/flightrecord"
            and getattr(self.p2p_node, "flight", None) is not None
        ):
            # operator dump trigger; body consumed for keep-alive framing
            post_data = self._read_body("/debug/flightrecord", t0)
            if post_data is None:
                return
            status, payload, _error = flightrecord_route(self.p2p_node)
            self._send_response(payload, status)
        elif (
            self.path == "/debug/faults"
            and getattr(self.p2p_node, "chaos_routes", False)
        ):
            # chaos-harness injector arming (ISSUE 14; CLI
            # --chaos-injector) — the PR 5 engine-seam faults over HTTP
            post_data = self._read_body("/debug/faults", t0)
            if post_data is None:
                return
            status, payload, _error = faults_route(
                self.p2p_node, post_data
            )
            self._send_response(payload, status)
        else:
            # unknown POST path: the body was never read — under keep-alive
            # its bytes would be parsed as the next request's start line,
            # so this connection must close after the reply
            self.close_connection = True
            self._send_response({"error": "Invalid endpoint"}, 404)

    def do_GET(self):
        self._begin_request()
        if self.path == "/stats":
            self._send_response(
                stats_payload(self.p2p_node, self.expose_serving)
            )
        elif self.path == "/network":
            self._send_response(self.p2p_node.network_view())
        elif self.path == "/metrics" and self.expose_metrics:
            self._send_response(metrics_payload(self.p2p_node))
        elif self.path in PROM_PATHS and self.expose_metrics:
            # the Prometheus exposition of the same body (shared core —
            # byte-identical on both transports)
            self._send_response(metrics_prom_payload(self.p2p_node))
        elif self.path == CLUSTER_PATH and self.expose_metrics:
            # the gossip-aggregated fleet view (ISSUE 10)
            self._send_response(cluster_payload(self.p2p_node))
        elif self.path in CLUSTER_PROM_PATHS and self.expose_metrics:
            self._send_response(cluster_prom_payload(self.p2p_node))
        elif (
            self.path == "/debug/trace"
            and getattr(self.p2p_node, "flight", None) is not None
        ):
            # the span ring as Perfetto-loadable trace-event JSON
            status, payload, _error = trace_export_route(self.p2p_node)
            self._send_response(payload, status)
        elif self.path == "/healthz":
            self._send_response(healthz_payload(self.p2p_node))
        elif self.path == "/readyz":
            status, payload = readyz_route(self.p2p_node)
            self._send_response(payload, status)
        else:
            self._send_response({"error": "Invalid endpoint"}, 404)

    def log_message(self, fmt, *args):  # route http.server chatter to logging
        logger.debug("%s - %s", self.address_string(), fmt % args)


def make_http_server(
    p2p_node,
    host: str,
    http_port: int,
    *,
    expose_metrics: bool = False,
    expose_batch: bool = False,
    expose_serving: bool = False,
    legacy_transport: bool = False,
    max_workers: int = 128,
):
    """Default: the lean keep-alive transport (net/fastserve.py) — a deep
    accept queue and ~an order of magnitude less pure-Python per request
    than http.server, feeding the coalescer the concurrency it batches.
    ``legacy_transport=True`` restores the seed's serving transport —
    stock http.server speaking HTTP/1.0 (a connection per request) on the
    stock 5-deep accept queue — for A/B measurement (bench.py --mode
    concurrent drives both under identical load). Both return the same
    lifecycle surface: serve_forever() / shutdown() / server_address.
    ``max_workers`` bounds the lean transport's connection-worker pool
    (net/fastserve.py; the legacy transport keeps the seed's unbounded
    thread-per-connection behavior — it exists to BE the seed, bit for
    bit)."""
    if legacy_transport:
        handler = type(
            "BoundHandler",
            (SudokuHTTPHandler,),
            {
                "p2p_node": p2p_node,
                "expose_metrics": expose_metrics,
                "expose_batch": expose_batch,
                "expose_serving": expose_serving,
                "protocol_version": "HTTP/1.0",
            },
        )
        httpd = ThreadingHTTPServer((host, http_port), handler)
    else:
        from .fastserve import FastHTTPServer

        httpd = FastHTTPServer(
            p2p_node,
            host,
            http_port,
            expose_metrics=expose_metrics,
            expose_batch=expose_batch,
            expose_serving=expose_serving,
            max_workers=max_workers,
        )
    logger.info("HTTP server on %s:%s", host, http_port)
    return httpd
