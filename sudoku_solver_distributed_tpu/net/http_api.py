"""HTTP API: POST /solve, GET /stats, GET /network — byte-identical bodies.

Response contract (reference node.py:661-704):
  POST /solve  200 → the solved grid as a JSON array-of-arrays;
               400 → {"error": "No solution found", "solution": null}
  GET  /stats  200 → the merged all_stats shape
  GET  /network 200 → the all_peers dict, or {self_id: []} when alone
  anything else 404 → {"error": "Invalid endpoint"}

Fixes behind the surface: a *threading* HTTP server, so /stats and /network
answer while a /solve is in flight (the reference's single-threaded server
blocks them — SURVEY.md §1 [verified live]); malformed /solve bodies get a
400 JSON error instead of the reference's uncaught exception + empty reply
(SURVEY.md §2 HTTP row [verified live]).
"""

from __future__ import annotations

import json
import logging
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)


def _board_error(sudoku, size: int) -> str | None:
    """Semantic body validation: reject JSON-valid-but-malformed boards
    before they reach the engine (VERDICT r4 task 2). The reference crashes
    uncaught on these — `board[row][col]` on a string, a ragged grid, or a
    non-9×9 grid raises in the handler thread and the client gets an empty
    reply (reference node.py:672-690 [verified live]). Returns a reason
    string when invalid, None when the board is a clean ``size``×``size``
    grid of ints in 0..size."""
    if not isinstance(sudoku, list) or len(sudoku) != size:
        return f"board must be a {size}x{size} array"
    for row in sudoku:
        if not isinstance(row, list) or len(row) != size:
            return f"board must be a {size}x{size} array"
        for v in row:
            if type(v) is not int or not 0 <= v <= size:
                return f"cells must be integers in 0..{size}"
    return None


class SudokuHTTPHandler(BaseHTTPRequestHandler):
    p2p_node = None       # set by make_http_server
    expose_metrics = False  # opt-in /metrics route (CLI --metrics); default
    #                         off keeps the 404 surface byte-identical
    expose_batch = False    # opt-in POST /solve_batch (CLI --batch-api):
    #                         the engine's bucketed batch path through HTTP
    MAX_BATCH = 4096        # board-count guard for /solve_batch
    MAX_BATCH_BYTES = 32 << 20  # body-size guard, checked before buffering

    def _send_response(self, content, status: int = 200) -> None:
        body = json.dumps(content).encode()
        self.send_response(status)
        self.send_header("Content-type", "application/json")
        self.end_headers()
        self.wfile.write(body)

    def _record(self, route: str, t0: float, error: bool = False) -> None:
        m = getattr(self.p2p_node, "metrics", None)
        if m is not None:
            m.record(route, time.perf_counter() - t0, error=error)

    def do_POST(self):
        t0 = time.perf_counter()
        if self.path == "/solve":
            initial_time = time.time()
            logger.info("received /solve POST request")
            try:
                content_length = int(self.headers.get("Content-Length", 0))
                post_data = self.rfile.read(content_length)
                sudoku = json.loads(post_data.decode("utf-8"))["sudoku"]
            except (ValueError, KeyError, TypeError, UnicodeDecodeError):
                # TypeError: a JSON-valid non-object body ([1,2,3], "foo")
                # makes body["sudoku"] a non-subscript access — same 400,
                # never a dead handler thread (code-review r5).
                # record before replying: a client may poll /metrics the
                # instant its response arrives
                self._record("/solve", t0, error=True)
                self._send_response({"error": "Invalid request"}, 400)
                return
            size = self.p2p_node.engine.spec.size
            reason = _board_error(sudoku, size)
            if reason is not None:
                logger.info("rejected /solve body: %s", reason)
                self._record("/solve", t0, error=True)
                self._send_response({"error": "Invalid request"}, 400)
                return
            solution = self.p2p_node.peer_sudoku_solve(sudoku)
            logger.info("execution time: %s", time.time() - initial_time)
            if solution:
                self._record("/solve", t0)
                self._send_response(solution)
            else:
                self._record("/solve", t0, error=True)
                self._send_response(
                    {"error": "No solution found", "solution": solution}, 400
                )
        elif self.path == "/solve_batch" and self.expose_batch:
            # Opt-in extension (not a reference surface): the engine's
            # bucketed batch path over HTTP — the framework's headline
            # strength (bench.py throughput) reachable by a serving
            # client, instead of one board per request. Body:
            # {"sudokus": [grid, ...]} → {"solutions": [grid|null, ...],
            # "solved": n, "capped": n}. null rows mean not solved;
            # capped counts rows whose search exhausted the iteration
            # budget (not finished ≠ proven unsatisfiable, engine.py).
            try:
                content_length = int(self.headers.get("Content-Length", 0))
                if content_length > self.MAX_BATCH_BYTES:
                    # bound memory BEFORE buffering the body: a batch
                    # endpoint invites large payloads (code-review r5);
                    # 4096 25x25 boards serialize to ~8 MB, so the cap
                    # is generous for every legitimate request
                    self._record("/solve_batch", t0, error=True)
                    self._send_response({"error": "Invalid request"}, 400)
                    return
                body = json.loads(self.rfile.read(content_length).decode())
                sudokus = body["sudokus"]
            except (ValueError, KeyError, TypeError, UnicodeDecodeError):
                self._record("/solve_batch", t0, error=True)
                self._send_response({"error": "Invalid request"}, 400)
                return
            size = self.p2p_node.engine.spec.size
            if (
                not isinstance(sudokus, list)
                or not 1 <= len(sudokus) <= self.MAX_BATCH
            ):
                reason = f"need 1..{self.MAX_BATCH} boards"
            else:
                reason = next(
                    filter(
                        None, (_board_error(s, size) for s in sudokus)
                    ),
                    None,
                )
            if reason is not None:
                logger.info("rejected /solve_batch body: %s", reason)
                self._record("/solve_batch", t0, error=True)
                self._send_response({"error": "Invalid request"}, 400)
                return
            solutions, mask, info = self.p2p_node.batch_sudoku_solve(sudokus)
            self._record("/solve_batch", t0)
            self._send_response(
                {
                    "solutions": [
                        sol.tolist() if ok else None
                        for sol, ok in zip(solutions, mask)
                    ],
                    "solved": int(mask.sum()),
                    "capped": info["capped"],
                }
            )
        else:
            self._send_response({"error": "Invalid endpoint"}, 404)

    def do_GET(self):
        if self.path == "/stats":
            self._send_response(self.p2p_node.get_stats())
        elif self.path == "/network":
            self._send_response(self.p2p_node.network_view())
        elif self.path == "/metrics" and self.expose_metrics:
            m = getattr(self.p2p_node, "metrics", None)
            body = m.summary() if m is not None else {}
            # engine health rides along (frontier fallbacks / serving-loop
            # liveness, engine.health) — route keys all start with "/", so
            # the extra key can't collide
            eng = getattr(self.p2p_node, "engine", None)
            if eng is not None and hasattr(eng, "health"):
                body["engine"] = eng.health()
            # membership churn machinery (tombstones / re-dial pool):
            # same no-collision argument as the engine block
            m_health = getattr(
                getattr(self.p2p_node, "membership", None), "health", None
            )
            if m_health is not None:
                body["membership"] = m_health()
            self._send_response(body)
        else:
            self._send_response({"error": "Invalid endpoint"}, 404)

    def log_message(self, fmt, *args):  # route http.server chatter to logging
        logger.debug("%s - %s", self.address_string(), fmt % args)


def make_http_server(
    p2p_node,
    host: str,
    http_port: int,
    *,
    expose_metrics: bool = False,
    expose_batch: bool = False,
) -> ThreadingHTTPServer:
    handler = type(
        "BoundHandler",
        (SudokuHTTPHandler,),
        {
            "p2p_node": p2p_node,
            "expose_metrics": expose_metrics,
            "expose_batch": expose_batch,
        },
    )
    httpd = ThreadingHTTPServer((host, http_port), handler)
    logger.info("HTTP server on %s:%s", host, http_port)
    return httpd
