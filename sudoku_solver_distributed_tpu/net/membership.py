"""Membership & topology: anchor join, flood merge, disconnect pruning.

Reproduces the reference's topology semantics (reference node.py:195-260,
334-381, 559-577):

  * a newcomer dials an anchor with ``connect``; the anchor records it in
    ``peers_out`` and replies ``connected``; the newcomer records the anchor
    in ``peers_in`` and notes ``all_peers[anchor] = [self]``;
  * ``all_peers`` ({parent: [children...]}) floods on every change with a
    grow-only union merge, until the network converges;
  * a node with only one link opportunistically dials a second peer
    (reference node.py:243-249);
  * on ``disconnect`` the departed address is pruned everywhere it appears,
    the change re-floods, and an orphaned child re-dials another node
    (reference node.py:344-372);
  * ``peers_to_reconnect`` tracks liveness flags exactly as the reference
    does (True on sight, False on disconnect, revived on re-sight).

Beyond the reference (churn-soak findings, tests/test_churn_soak.py):

  * **tombstones** — a pruned address is remembered dead for
    ``tombstone_ttl_s``; the grow-only union merge filters tombstoned
    addresses from incoming floods, so a node holding a stale pre-death
    view can no longer *resurrect* a dead peer network-wide by re-flooding
    it (the add-wins race the reference's merge loses permanently,
    reference node.py:227-231). Direct evidence of life (any datagram
    from the address — ``mark_alive``) clears the tombstone instantly, so
    a false-positive death or a genuine rejoin heals on first contact.
  * **stale-flood pushback** — tombstoned addresses seen in an incoming
    flood are reported to the caller (``drain_stale``), which answers the
    sender's neighborhood with ``disconnect`` relays: the deletion chases
    the stale view instead of waiting for the holder to stumble on it.
  * **orphan re-dial** — ``reconnect_candidate`` rotates through
    ``peers_to_reconnect`` so a fully-orphaned node (e.g. the original
    anchor after every neighbor died: it has no ``anchor_node`` to retry)
    re-dials remembered addresses until the network heals. The reference
    keeps this very structure and never dials from it (SURVEY.md §5).

Tombstone TTL tradeoff (``tombstone_ttl_s``, default 30 s): the TTL
bounds BOTH how long a same-address rejoin churns against third-party
tombstones (direct contact heals instantly; distant nodes filter the
rejoin from floods until their tombstones expire) AND the protection
window against resurrection — a node stalled/partitioned for longer
than the TTL while a peer died can re-introduce the dead non-neighbor
entry via its later floods, after which nothing reaps it (heartbeats
watch neighbors only). That residual leak is strictly better than the
reference, which leaks EVERY dead peer in EVERY view permanently
(SURVEY.md §3.5 [verified live]); deployments with long GC/compile
stalls should raise the TTL, accepting slower distant-rejoin
visibility.

The ``all_peers`` dict is the GET /network body — byte-identical shape.
Thread-safe behind one lock (the reference mutates these sets from two
threads, unlocked).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Set

from .wire import valid_address

logger = logging.getLogger(__name__)


class Membership:
    def __init__(
        self,
        node_id: str,
        tombstone_ttl_s: float = 30.0,
        max_known_addresses: int = 4096,
    ):
        self.node_id = node_id
        self.tombstone_ttl_s = tombstone_ttl_s
        # Hostile-flood memory bound (ADVICE r5 low): ingress validation
        # keeps garbage out, but a flood of WELL-FORMED fake "host:port"
        # strings would still grow all_peers and peers_to_reconnect without
        # limit (the grow-only union merge never removes, and the re-dial
        # pool remembers every address it sees). Past this many distinct
        # addresses, merge_all_peers refuses new ones (logged); remembered
        # non-view addresses additionally age out past the same 10x-TTL
        # horizon node._reap_dead_neighbors uses for _last_seen.
        self.max_known_addresses = max_known_addresses
        self._lock = threading.Lock()
        self.peers_out: Set[str] = set()   # peers that dialed us
        self.peers_in: Set[str] = set()    # peers we dialed
        self.all_peers: Dict[str, List[str]] = {}
        self.peers_to_reconnect: Dict[str, bool] = {}
        self._remembered_at: Dict[str, float] = {}  # re-dial pool refresh time
        self._tombstones: Dict[str, float] = {}  # addr -> monotonic expiry
        self._buried_at: Dict[str, float] = {}   # addr -> first burial time
        self._stale_seen: List[str] = []         # pushback queue (drain_stale)
        self._redial_rotation: int = 0
        self._missing_rotation: int = 0

    # -- join --------------------------------------------------------------
    def on_connect(self, address: str) -> None:
        """Inbound ``connect`` (we are the anchor side). A live dial is
        ground truth: it clears any tombstone for the dialer."""
        with self._lock:
            self._tombstones.pop(address, None)
            self._buried_at.pop(address, None)  # revival resets burial age
            self.peers_out.add(address)
            self.peers_to_reconnect[address] = True
            self._remembered_at[address] = time.monotonic()

    def on_connected(self, address: str) -> None:
        """Inbound ``connected`` (our dial was accepted)."""
        with self._lock:
            self._tombstones.pop(address, None)
            self._buried_at.pop(address, None)
            self.peers_in.add(address)
            self.peers_to_reconnect[address] = True
            self._remembered_at[address] = time.monotonic()
            self.all_peers[address] = [self.node_id]

    def mark_alive(self, address: str) -> None:
        """Direct evidence of life (a datagram FROM ``address``): clear its
        tombstone so a false-positive death heals on first contact."""
        with self._lock:
            self._tombstones.pop(address, None)
            self._buried_at.pop(address, None)

    # -- flood merge -------------------------------------------------------
    def merge_all_peers(self, received: Dict[str, List[str]]) -> bool:
        """Union merge with tombstone filtering; True if our view changed
        (=> re-flood). Tombstoned addresses in ``received`` are recorded
        for ``drain_stale`` pushback instead of being merged."""
        changed = False
        now = time.monotonic()
        with self._lock:
            self._purge_tombstones(now)
            self._gc_remembered_locked(now)
            # Address budget (ADVICE r5 low): a flood of well-formed fake
            # addresses must not grow the view without bound. Entries past
            # the cap are refused wholesale — in a legitimate network the
            # cap is orders of magnitude above the node count, and a later
            # flood re-offers anything a hostile burst crowded out.
            known = self._total_peers_locked()
            budget = self.max_known_addresses - len(known)
            refused = 0
            stale = set()
            for parent, children in received.items():
                if not valid_address(parent) or not isinstance(
                    children, list
                ):
                    continue  # hostile/corrupt flood entry (wire-fuzz)
                live_children = []
                for addr in children:
                    if not valid_address(addr):
                        continue
                    if addr in self._tombstones:
                        stale.add(addr)
                        self._renew_tombstone_locked(addr, now)
                    else:
                        live_children.append(addr)
                if parent in self._tombstones:
                    stale.add(parent)
                    self._renew_tombstone_locked(parent, now)
                    # the parent is dead but its children may be live
                    # survivors only ever advertised through it — remember
                    # them as re-dial candidates even though there is no
                    # live edge to merge them under (code-review r5)
                    for addr in live_children:
                        if addr != self.node_id and self.peers_to_reconnect.get(
                            addr
                        ) is not True:
                            if (
                                addr in self.peers_to_reconnect
                                or len(self.peers_to_reconnect)
                                < self.max_known_addresses
                            ):
                                self.peers_to_reconnect[addr] = True
                                self._remembered_at[addr] = now
                    continue
                if parent not in self.all_peers:
                    # an entry whose every child was tombstone-filtered is
                    # itself stale — adding {parent: []} would pollute the
                    # view (pruning deletes emptied parents)
                    if live_children or not children:
                        new = {
                            a
                            for a in (parent, *live_children)
                            if a not in known and a != self.node_id
                        }
                        if len(new) > budget:
                            refused += len(new)
                            continue
                        budget -= len(new)
                        known |= new
                        self.all_peers[parent] = list(live_children)
                        changed = True
                else:
                    have = set(self.all_peers[parent])
                    allowed = []
                    for addr in live_children:
                        if addr in have:
                            continue
                        if addr in known or addr == self.node_id:
                            allowed.append(addr)
                        elif budget > 0:
                            budget -= 1
                            known.add(addr)
                            allowed.append(addr)
                        else:
                            refused += 1
                    if allowed:
                        self.all_peers[parent] = sorted(have | set(allowed))
                        changed = True
            if refused:
                logger.warning(
                    "flood merge refused %d new addresses past the "
                    "%d-address view cap",
                    refused,
                    self.max_known_addresses,
                )
            self._stale_seen.extend(
                a for a in sorted(stale) if a not in self._stale_seen
            )
            # revive liveness flags for any address we can now see, and
            # REMEMBER every address (reconnect_candidate's pool: a node
            # orphaned later must be able to re-dial survivors it only
            # ever knew transitively, not just its own ex-neighbors).
            # The view itself is capped above, so this pool's growth from
            # here is bounded by the same budget.
            for parent, children in self.all_peers.items():
                for addr in (parent, *children):
                    if addr == self.node_id:
                        continue
                    self._remembered_at[addr] = now
                    if self.peers_to_reconnect.get(addr) is not True:
                        self.peers_to_reconnect[addr] = True
        return changed

    def _gc_remembered_locked(self, now: float) -> None:
        """Age out remembered addresses that are neither neighbors nor in
        the current view and have not been re-attested within 10x the
        tombstone TTL — the same horizon node._reap_dead_neighbors applies
        to ``_last_seen``. Without this, every address a hostile flood
        ever slipped into the re-dial pool (or every long-dead ex-peer)
        would be remembered forever (ADVICE r5 low); with it the pool
        self-heals once the flood stops, and the view cap's budget frees
        back up."""
        horizon = 10.0 * self.tombstone_ttl_s
        keep = self._total_peers_locked() | self.peers_in | self.peers_out
        for addr in list(self.peers_to_reconnect):
            if addr in keep:
                continue
            t0 = self._remembered_at.setdefault(addr, now)
            if now - t0 > horizon:
                del self.peers_to_reconnect[addr]
                del self._remembered_at[addr]
        # drop orphaned timestamps (address left the pool some other way)
        for addr in [
            a for a in self._remembered_at if a not in self.peers_to_reconnect
        ]:
            del self._remembered_at[addr]

    def drain_stale(self) -> List[str]:
        """Tombstoned addresses observed in incoming floods since the last
        drain — the caller relays ``disconnect`` for each so the deletion
        reaches whichever node still holds the stale view."""
        with self._lock:
            out, self._stale_seen = self._stale_seen, []
            return out

    def live_tombstones(self) -> List[str]:
        """Currently-tombstoned addresses (for the periodic deletion
        re-broadcast): tombstones are NODE-LOCAL state, so a node that
        joins after a death has none and any stale view reaching it
        resurrects the dead peer permanently (extended churn soak, seed
        101). Re-relaying ``disconnect`` for live tombstones every
        anti-entropy tick makes the deletion a rumor with the same
        lifetime as the tombstone — joiners and stale holders both get
        re-killed copies for the whole TTL."""
        with self._lock:
            self._purge_tombstones(time.monotonic())
            return sorted(self._tombstones)

    def _renew_tombstone_locked(self, addr: str, now: float) -> None:
        """Seeing a tombstoned address still CIRCULATING in a flood means
        some node holds a stale copy — extend the deletion memory so it
        outlives the circulation (extended churn soak, seed 101: fixed
        TTLs expired while a stale view survived, and the dead peer
        resurrected permanently). Capped at 6x TTL from first burial so
        a same-address rejoin is delayed at most that long at distant
        nodes (direct contact still heals instantly via mark_alive, and
        nodes that heard the address recently REFUSE deletion rumors —
        node._on_disconnect)."""
        cap = self._buried_at.get(addr, now) + 6.0 * self.tombstone_ttl_s
        self._tombstones[addr] = min(now + self.tombstone_ttl_s, cap)

    def _purge_tombstones(self, now: float) -> None:
        for addr in [a for a, t in self._tombstones.items() if t < now]:
            del self._tombstones[addr]
        # the burial record outlives the tombstone by the full renewal cap:
        # a re-infection (neighbor's re-broadcast right after our purge)
        # then RESUMES the capped clock instead of restarting it — without
        # this, holders with staggered burial windows could alternately
        # re-infect each other and flap a live rejoined address in and out
        # of distant views without bound (code-review r5)
        horizon = 6.0 * self.tombstone_ttl_s
        for addr in [
            a
            for a, t0 in self._buried_at.items()
            if a not in self._tombstones and now - t0 > horizon
        ]:
            del self._buried_at[addr]

    def second_link_target(self) -> Optional[str]:
        """If singly-connected, an address worth dialing for redundancy
        (reference node.py:243-249)."""
        with self._lock:
            if not (len(self.peers_in) == 1 or len(self.peers_out) == 1):
                return None
            for parent in self.all_peers:
                if (
                    parent not in self.peers_in
                    and parent not in self.peers_out
                    and parent != self.node_id
                ):
                    return parent
        return None

    # -- departure ---------------------------------------------------------
    def on_disconnect(self, address: str) -> tuple[bool, Optional[str]]:
        """Prune a departed peer.

        Returns (changed, redial): changed => our all_peers view shrank and
        should re-flood; redial is an address to dial if the departed peer
        was our parent (orphan re-join, reference node.py:360-372).
        """
        redial: Optional[str] = None
        if address == self.node_id:
            # We can never "depart" from our own view, and tombstoning our
            # own id would filter US out of every incoming flood merge.
            # Defense in depth behind the node-level ingress drop of spoofed
            # self-disconnects (node._on_message): every other path into
            # on_disconnect (dead-neighbor declarations, relayed deletions)
            # names a peer, so a self-address here is always hostile or a
            # bug (ADVICE r5 high).
            return False, None
        with self._lock:
            now = time.monotonic()
            self._purge_tombstones(now)
            self.peers_in.discard(address)
            self.peers_out.discard(address)

            before = {k: list(v) for k, v in self.all_peers.items()}
            was_parent_of_us = address in before and self.node_id in before[address]

            for parent in list(self.all_peers):
                children = self.all_peers[parent]
                if address in children:
                    children.remove(address)
                    if not children:
                        del self.all_peers[parent]
            self.all_peers.pop(address, None)
            changed = before != self.all_peers

            if changed:
                self.peers_to_reconnect[address] = False
                self._buried_at.setdefault(address, now)
                # Tombstone only when the disconnect actually changed our
                # view: a relayed pushback about an already-pruned address
                # must NOT renew the tombstone, or mutually-renewing relays
                # could exclude a same-address rejoin indefinitely
                # (code-review r5). Worst case after a rejoin inside the
                # TTL: ~one TTL of pushback churn, then the un-renewed
                # tombstones expire and the rejoin merges everywhere.
                self._tombstones[address] = now + self.tombstone_ttl_s

            if was_parent_of_us:
                # never redial ourselves (a key == node_id appears whenever
                # someone's second-link flood records us as a parent; a
                # self-dial would handshake with ourselves and write a
                # {self: [self]} loop into every view — verify r5) nor the
                # peer that just departed
                for candidate in self.all_peers:
                    if candidate not in (self.node_id, address):
                        redial = candidate
                        break
                else:
                    for sibling in before.get(address, []):
                        if sibling != self.node_id:
                            redial = sibling
                            break
        return changed, redial

    def reconnect_candidate(self) -> Optional[str]:
        """An address worth re-dialing when we have no neighbors left.

        Rotates through ``peers_to_reconnect`` (the reference's own
        remembered-peers structure, which it populates but never dials
        from — SURVEY.md §5), preferring addresses last seen alive (flag
        True) and skipping currently-tombstoned ones. Returns None when
        nothing is remembered."""
        with self._lock:
            self._purge_tombstones(time.monotonic())
            known = [
                a
                for a in self.peers_to_reconnect
                if a != self.node_id and a not in self._tombstones
            ]
            if not known:
                return None
            known.sort(
                key=lambda a: (not self.peers_to_reconnect.get(a, False), a)
            )
            self._redial_rotation += 1
            return known[self._redial_rotation % len(known)]

    def missing_candidate(self) -> Optional[str]:
        """A remembered, non-tombstoned address absent from the current
        view — the partition-repair dial target. A bridge node's death
        can split the overlay into camps that are each internally content
        (every node keeps neighbors, so the orphan re-dial never fires)
        yet permanently partitioned (extended churn soak, seed 101);
        occasionally dialing a remembered absentee re-merges the camps.
        Dead absentees cost one ignored connect datagram each."""
        with self._lock:
            self._purge_tombstones(time.monotonic())
            known = self._total_peers_locked()
            missing = [
                a
                for a in self.peers_to_reconnect
                if a != self.node_id
                and a not in known
                and a not in self._tombstones
            ]
            if not missing:
                return None
            # flag-True (last seen alive) first: repair latency must not
            # scale with the count of permanently-dead remembered
            # addresses (code-review r5)
            missing.sort(
                key=lambda a: (not self.peers_to_reconnect.get(a, False), a)
            )
            self._missing_rotation += 1
            live_count = sum(
                1 for a in missing if self.peers_to_reconnect.get(a, False)
            )
            pool = missing[:live_count] if live_count else missing
            return pool[self._missing_rotation % len(pool)]

    # -- views -------------------------------------------------------------
    def neighbors(self) -> List[str]:
        """Directly-connected peers (the flood/gossip fan-out set,
        reference node.py:574, 593)."""
        with self._lock:
            return list(self.peers_out) + list(self.peers_in)

    def total_peers(self) -> List[str]:
        """Every known address except ourselves (the task-farm worker pool,
        reference node.py:251-260)."""
        with self._lock:
            return sorted(self._total_peers_locked())

    def _total_peers_locked(self) -> set:
        """Union of parents and children minus self; callers hold _lock.
        ONE definition shared by total_peers and health (code-review r5)."""
        total = set(self.all_peers.keys())
        for children in self.all_peers.values():
            total.update(children)
        total.discard(self.node_id)
        return total

    def network_view(self) -> Dict[str, List[str]]:
        """The GET /network body (reference node.py:696-702)."""
        with self._lock:
            if self.all_peers:
                return {k: list(v) for k, v in self.all_peers.items()}
            return {self.node_id: []}

    def health(self) -> dict:
        """Operator view of the churn machinery (GET /metrics
        ``membership`` block): live tombstones mean recent deaths are
        being held out of flood merges; ``remembered`` is the orphan
        re-dial pool."""
        with self._lock:
            self._purge_tombstones(time.monotonic())
            return {
                # distinct peers: a pair that dialed each other lands in
                # both sets (code-review r5)
                "neighbors": len(self.peers_in | self.peers_out),
                "known_peers": len(self._total_peers_locked()),
                "tombstones": len(self._tombstones),
                "remembered": len(self.peers_to_reconnect),
            }
