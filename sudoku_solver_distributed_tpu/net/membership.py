"""Membership & topology: anchor join, flood merge, disconnect pruning.

Reproduces the reference's topology semantics (reference node.py:195-260,
334-381, 559-577):

  * a newcomer dials an anchor with ``connect``; the anchor records it in
    ``peers_out`` and replies ``connected``; the newcomer records the anchor
    in ``peers_in`` and notes ``all_peers[anchor] = [self]``;
  * ``all_peers`` ({parent: [children...]}) floods on every change with a
    grow-only union merge, until the network converges;
  * a node with only one link opportunistically dials a second peer
    (reference node.py:243-249);
  * on ``disconnect`` the departed address is pruned everywhere it appears,
    the change re-floods, and an orphaned child re-dials another node
    (reference node.py:344-372);
  * ``peers_to_reconnect`` tracks liveness flags exactly as the reference
    does (True on sight, False on disconnect, revived on re-sight).

The ``all_peers`` dict is the GET /network body — byte-identical shape.
Thread-safe behind one lock (the reference mutates these sets from two
threads, unlocked).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set



class Membership:
    def __init__(self, node_id: str):
        self.node_id = node_id
        self._lock = threading.Lock()
        self.peers_out: Set[str] = set()   # peers that dialed us
        self.peers_in: Set[str] = set()    # peers we dialed
        self.all_peers: Dict[str, List[str]] = {}
        self.peers_to_reconnect: Dict[str, bool] = {}

    # -- join --------------------------------------------------------------
    def on_connect(self, address: str) -> None:
        """Inbound ``connect`` (we are the anchor side)."""
        with self._lock:
            self.peers_out.add(address)
            self.peers_to_reconnect[address] = True

    def on_connected(self, address: str) -> None:
        """Inbound ``connected`` (our dial was accepted)."""
        with self._lock:
            self.peers_in.add(address)
            self.peers_to_reconnect[address] = True
            self.all_peers[address] = [self.node_id]

    # -- flood merge -------------------------------------------------------
    def merge_all_peers(self, received: Dict[str, List[str]]) -> bool:
        """Grow-only union merge; True if our view changed (=> re-flood)."""
        changed = False
        with self._lock:
            for parent, children in received.items():
                if parent not in self.all_peers:
                    self.all_peers[parent] = list(children)
                    changed = True
                else:
                    merged = sorted(set(self.all_peers[parent]) | set(children))
                    if merged != sorted(self.all_peers[parent]):
                        self.all_peers[parent] = merged
                        changed = True
            # revive liveness flags for any address we can now see
            for parent, children in self.all_peers.items():
                for addr in (parent, *children):
                    if self.peers_to_reconnect.get(addr) is False:
                        self.peers_to_reconnect[addr] = True
        return changed

    def second_link_target(self) -> Optional[str]:
        """If singly-connected, an address worth dialing for redundancy
        (reference node.py:243-249)."""
        with self._lock:
            if not (len(self.peers_in) == 1 or len(self.peers_out) == 1):
                return None
            for parent in self.all_peers:
                if (
                    parent not in self.peers_in
                    and parent not in self.peers_out
                    and parent != self.node_id
                ):
                    return parent
        return None

    # -- departure ---------------------------------------------------------
    def on_disconnect(self, address: str) -> tuple[bool, Optional[str]]:
        """Prune a departed peer.

        Returns (changed, redial): changed => our all_peers view shrank and
        should re-flood; redial is an address to dial if the departed peer
        was our parent (orphan re-join, reference node.py:360-372).
        """
        redial: Optional[str] = None
        with self._lock:
            self.peers_in.discard(address)
            self.peers_out.discard(address)

            before = {k: list(v) for k, v in self.all_peers.items()}
            was_parent_of_us = address in before and self.node_id in before[address]

            for parent in list(self.all_peers):
                children = self.all_peers[parent]
                if address in children:
                    children.remove(address)
                    if not children:
                        del self.all_peers[parent]
            self.all_peers.pop(address, None)
            changed = before != self.all_peers

            if changed:
                self.peers_to_reconnect[address] = False

            if was_parent_of_us:
                if self.all_peers:
                    redial = next(iter(self.all_peers))
                else:
                    for sibling in before.get(address, []):
                        if sibling != self.node_id:
                            redial = sibling
                            break
        return changed, redial

    # -- views -------------------------------------------------------------
    def neighbors(self) -> List[str]:
        """Directly-connected peers (the flood/gossip fan-out set,
        reference node.py:574, 593)."""
        with self._lock:
            return list(self.peers_out) + list(self.peers_in)

    def total_peers(self) -> List[str]:
        """Every known address except ourselves (the task-farm worker pool,
        reference node.py:251-260)."""
        with self._lock:
            total = set(self.all_peers.keys())
            for children in self.all_peers.values():
                total.update(children)
            total.discard(self.node_id)
            return sorted(total)

    def network_view(self) -> Dict[str, List[str]]:
        """The GET /network body (reference node.py:696-702)."""
        with self._lock:
            if self.all_peers:
                return {k: list(v) for k, v in self.all_peers.items()}
            return {self.node_id: []}
